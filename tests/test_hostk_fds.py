"""Unified fd space (round-2 verdict item 7; reference
descriptor_table.rs:12): virtual fds are allocated POSIX lowest-free in
the real fd number space — interleaving with native passthrough files,
below FD_SETSIZE for select(), and dup2()-able onto stdio. The guest's
stdout (which prints the fd numbers) must match a native run exactly."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def fd_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("fd") / "fd_guest"
    subprocess.run(["cc", "-O2", "-o", str(out), str(GUESTS / "fd_guest.c")], check=True)
    return str(out)


def test_fd_guest_matches_native(tmp_path, fd_bin):
    d = tmp_path / "native"
    d.mkdir()
    native = subprocess.run([fd_bin], capture_output=True, cwd=d)
    assert native.returncode == 0, native.stdout.decode() + native.stderr.decode()
    assert b"fds 3 4 5 3\n" in native.stdout  # the POSIX numbering itself

    graph = two_node_graph(10, 0.0)
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["h"], host_nodes=[0], data_dir=tmp_path / "sh")
    p = k.add_process(ProcessSpec(host="h", args=[fd_bin]))
    try:
        k.run(20 * NS_PER_SEC)
    finally:
        k.shutdown()
    assert p.exit_code == 0, p.stdout().decode() + p.stderr().decode()
    assert p.stdout() == native.stdout
