"""Adaptive conservative windows (engine/round.py _next_window_end):
window_end = min over hosts of (next event time + per-node lookahead) —
the LBTS bound — must be LEAF-IDENTICAL to fixed-width rounds: the
delivery clamp max(t + lat, window_end) provably never binds under the
bound, so widening the window regroups rounds without moving a single
event, draw, or byte. Pinned here on phold + tgen across
plain/pump/megakernel, sharded, ensemble slices, and through a
checkpoint roundtrip; plus the perf pin — a sparse-in-time scenario
drains in provably fewer iterations/rounds.

What may legitimately differ between window policies (and is therefore
canonicalized/excluded): queue/outbox slot PLACEMENT and dead-slot
tombstones (flush batching differs; pops are key-driven so placement is
semantically void — same normalization as tests/test_pump.py), and the
round-structure diagnostics iters_done / lanes_live / win_ns_sum /
tracker round counters / occupancy high-water marks (fewer, wider
rounds is the point)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_pump import _normalize
from test_pump import _world as _tgen_world

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import (
    ChunkProbe,
    bootstrap,
    run_until,
    state_probe,
)
from shadow_tpu.engine.state import state_from_host, state_to_host
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.phold import PholdModel
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

# host nodes (0, 1) talk over 20 ms links; nodes 2-3 carry the graph's
# 1 ms minimum-latency edge but host no traffic — so the fixed
# conservative width is 1 ms while every host's true lookahead is 20 ms
HETERO_GML = "\n".join(
    [
        "graph [",
        "  directed 0",
        *[f"  node [ id {i} ]" for i in range(4)],
        '  edge [ source 0 target 0 latency "20 ms" ]',
        '  edge [ source 1 target 1 latency "20 ms" ]',
        '  edge [ source 0 target 1 latency "20 ms" ]',
        '  edge [ source 2 target 3 latency "1 ms" ]',
        '  edge [ source 2 target 2 latency "1 ms" ]',
        '  edge [ source 3 target 3 latency "1 ms" ]',
        "]",
    ]
)


def _hetero_world(num_hosts, max_delay_ms=50):
    graph = NetworkGraph.from_gml(HETERO_GML)
    tables = compute_routing(graph).with_hosts(
        [i % 2 for i in range(num_hosts)]
    )
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=32,
        runahead_ns=graph.min_latency_ns(),
        seed=9,
        # tracker on: populates the probe's rounds_live (the mean-width
        # denominator) and widens the leaf-equivalence pins to the
        # tracker plane
        tracker=True,
    )
    model = PholdModel(
        num_hosts=num_hosts,
        min_delay_ns=1 * NS_PER_MS,
        max_delay_ns=max_delay_ms * NS_PER_MS,
    )
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    return cfg, model, tables, st


def _canon(st):
    """test_pump's queue normalization + zero every round-structure
    diagnostic a different window policy legitimately changes, and mask
    outbox tombstones (the outbox is empty after the final flush, but
    dead slots keep batching-dependent garbage)."""
    st = _normalize(st)
    ob = st.outbox
    v = np.asarray(ob.valid)
    assert not v.any(), "outbox should be flushed at run end"
    ob = ob.replace(
        dst=jnp.zeros_like(ob.dst),
        time=jnp.full_like(ob.time, 0),
        tie=jnp.zeros_like(ob.tie),
        data=jnp.zeros_like(ob.data),
        aux=jnp.zeros_like(ob.aux),
    )
    return st.replace(
        outbox=ob,
        win_ns_sum=st.win_ns_sum * 0,
        tracker=st.tracker.replace(
            rounds_live=st.tracker.rounds_live * 0,
            rounds_idle=st.tracker.rounds_idle * 0,
            queue_hwm=st.tracker.queue_hwm * 0,
            outbox_hwm=st.tracker.outbox_hwm * 0,
            exch_hwm=st.tracker.exch_hwm * 0,
        ),
    )


def _assert_canon_equal(a, b):
    fa = jax.tree_util.tree_leaves_with_path(_canon(a))
    fb = jax.tree.leaves(_canon(b))
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        assert jnp.array_equal(la, lb), (
            f"mismatch at {jax.tree_util.keystr(path)}"
        )


def _probe(st) -> ChunkProbe:
    return ChunkProbe.from_array(np.asarray(jax.jit(state_probe)(st)))


def test_adaptive_leaf_identical_and_fewer_iters_phold():
    """The tentpole pin, one pair of runs: on the sparse-in-time phold
    world the adaptive engine must (a) produce leaf-identical simulation
    state and (b) drain in >= 2x fewer pop-iterations (the published
    acceptance bar; the win here is ~3.7x)."""
    cfg, model, tables, st0 = _hetero_world(32)
    end = int(0.6 * NS_PER_SEC)
    adaptive = run_until(st0, end, model, tables, cfg, rounds_per_chunk=8)
    fixed = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg, adaptive_window=False),
        rounds_per_chunk=8,
    )
    pa, pf = _probe(adaptive), _probe(fixed)
    assert pa.events_handled == pf.events_handled > 0
    assert pa.iters * 2 <= pf.iters, (pa.iters, pf.iters)
    # windows actually widened: the mean LIVE window is a multiple of the
    # fixed 1 ms conservative width (it tracks the hosts' 20 ms lookahead)
    assert pf.window_ns_mean > 0
    assert pa.window_ns_mean > 2 * pf.window_ns_mean, (
        pa.window_ns_mean, pf.window_ns_mean
    )
    _assert_canon_equal(adaptive, fixed)


def test_adaptive_gated_off_under_dynamic_runahead():
    """Under use_dynamic_runahead the round-end delivery clamp MOVES
    delivery times (that IS the approximation), so window width is
    semantics-bearing there and _next_window_end must ignore
    adaptive_window — the combination would silently change
    trajectories for pre-existing dynamic-runahead configs."""
    from shadow_tpu.engine.round import _next_window_end

    cfg, model, tables, st0 = _hetero_world(8)
    end = int(NS_PER_SEC)
    fixed = _next_window_end(
        st0, end, dataclasses.replace(cfg, adaptive_window=False), None,
        tables=tables,
    )
    dyn = _next_window_end(
        st0, end, dataclasses.replace(cfg, use_dynamic_runahead=True), None,
        tables=tables,
    )
    widened = _next_window_end(st0, end, cfg, None, tables=tables)
    # the gate holds the dynamic window at the fixed floor…
    assert int(dyn) == int(fixed)
    # …which adaptive would otherwise have widened on this topology
    assert int(widened) > int(fixed)


class _ChunkTap:
    """Minimal on_state tap (the StateTap interface _drive consumes):
    commit the first verified chunk-boundary snapshot, then stand down."""

    def __init__(self):
        self.snaps = []

    def due(self, probe, chunk):
        return not self.snaps

    def commit(self, host_state):
        self.snaps.append(host_state)

    def interrupted(self):
        return False


def test_adaptive_checkpoint_roundtrip_leaf_exact():
    """Adaptive runs resume bit-exact: snapshot at a chunk boundary of
    the straight run (the checkpoint machinery's seam — _drive's
    on_state tap, through the state_to_host/state_from_host wire format),
    resume from the snapshot to the same end, and match the
    uninterrupted run on EVERY leaf — diagnostics included. The snapshot
    must come from a chunk boundary, not a separate run to `mid`: an
    end-clamped window at `mid` would legitimately regroup rounds."""
    cfg, model, tables, st0 = _hetero_world(16)
    end = int(0.4 * NS_PER_SEC)
    tap = _ChunkTap()
    straight = run_until(
        st0, end, model, tables, cfg, rounds_per_chunk=8, on_state=tap
    )
    assert tap.snaps, "run ended before a chunk-boundary snapshot landed"
    restored = state_from_host(tap.snaps[0], st0)
    assert int(np.asarray(restored.now)) < end, "snapshot was not mid-run"
    resumed = run_until(restored, end, model, tables, cfg, rounds_per_chunk=8)
    fa = jax.tree_util.tree_leaves_with_path(straight)
    fb = jax.tree.leaves(resumed)
    for (path, la), lb in zip(fa, fb):
        if jnp.issubdtype(getattr(la, "dtype", None), jax.dtypes.prng_key):
            la, lb = jax.random.key_data(la), jax.random.key_data(lb)
        assert jnp.array_equal(la, lb), (
            f"mismatch at {jax.tree_util.keystr(path)}"
        )


@pytest.mark.parametrize("engine,pump_k", [("plain", 0), ("pump", 4), ("megakernel", 4)])
def test_adaptive_matches_fixed_tgen_engines(engine, pump_k):
    """tgen (TCP + shaping + loss) under every engine: adaptive must
    equal the fixed-width PLAIN reference after canonicalization — one
    assertion covering both the window policy and the engine."""
    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    end = 40 * NS_PER_MS
    ref = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, adaptive_window=False),
        rounds_per_chunk=8,
    )
    got = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, engine=engine, pump_k=pump_k),
        rounds_per_chunk=8,
    )
    assert int(np.asarray(got.events_handled).sum()) > 0
    _assert_canon_equal(ref, got)


def test_adaptive_matches_fixed_sharded():
    """The window agreement stays mesh-uniform: an 8-shard adaptive run
    equals the single-device fixed-width run canonically."""
    from jax.sharding import Mesh

    from shadow_tpu.engine.sharded import AXIS, ShardedRunner

    assert jax.device_count() == 8
    cfg, model, tables, st0 = _hetero_world(16, max_delay_ms=20)
    end = int(0.15 * NS_PER_SEC)
    fixed_single = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg, adaptive_window=False),
        rounds_per_chunk=8,
    )
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=8)
    adaptive_sharded = runner.run_until(st0, end)
    _assert_canon_equal(fixed_single, adaptive_sharded)


def test_adaptive_matches_fixed_ensemble_slices():
    """Every replica of an adaptive ensemble equals its fixed-width
    counterpart canonically (the per-replica window min under vmap)."""
    from shadow_tpu.engine.ensemble import (
        init_ensemble_state,
        replica_slice,
        run_ensemble_until,
    )

    cfg, model, tables, _ = _hetero_world(8, max_delay_ms=20)
    end = int(0.15 * NS_PER_SEC)
    ens0 = init_ensemble_state(cfg, model, 2)
    adaptive = run_ensemble_until(
        ens0, end, model, tables, cfg, rounds_per_chunk=8
    )
    fixed = run_ensemble_until(
        ens0, end, model, tables,
        dataclasses.replace(cfg, adaptive_window=False),
        rounds_per_chunk=8,
    )
    for r in range(2):
        _assert_canon_equal(
            replica_slice(adaptive, r), replica_slice(fixed, r)
        )
