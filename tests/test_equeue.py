"""Event-queue semantics: pop order must equal the reference total order
(time, Packet<Local, src_host, seq) — reference src/main/core/work/event.rs:104-155 —
validated property-style against a plain Python sorted list."""

import random

import jax.numpy as jnp
import numpy as np

from shadow_tpu import equeue
from shadow_tpu.equeue import PAYLOAD_LANES
from shadow_tpu.events import KIND_PACKET, pack_tie, tie_seq, tie_src_host, tie_is_local
from shadow_tpu.simtime import TIME_MAX


def _mk_events(rng, n, num_hosts, seq_base=0):
    evs = []
    for i in range(n):
        t = rng.randrange(0, 50)
        kind = rng.choice([KIND_PACKET, 1, 2])
        src = rng.randrange(num_hosts)
        seq = seq_base + i
        data = [rng.randrange(100) for _ in range(PAYLOAD_LANES)]
        evs.append((t, kind, src, seq, data))
    return evs


def test_tie_packing_roundtrip():
    tie = pack_tie(3, 12345, 678)
    assert tie_src_host(tie) == 12345
    assert tie_seq(tie) == 678
    assert tie_is_local(tie) == 1
    tie_p = pack_tie(KIND_PACKET, 1, 2)
    assert tie_is_local(tie_p) == 0
    assert tie_p < tie  # packets sort before locals at equal time


def test_push_pop_single_host_matches_sorted_order():
    rng = random.Random(7)
    H, Q, N = 3, 64, 40
    q = equeue.create(H, Q)
    expect = {h: [] for h in range(H)}
    evs = _mk_events(rng, N, H)
    for t, kind, src, seq, data in evs:
        dsth = rng.randrange(H)
        tie = pack_tie(kind, src, seq)
        q = equeue.push_many(
            q,
            dst=jnp.array([dsth], jnp.int32),
            valid=jnp.array([True]),
            time=jnp.array([t], jnp.int64),
            tie=jnp.array([tie], jnp.int64),
            kind=jnp.array([kind], jnp.int32),
            data=jnp.array([data], jnp.int32),
        )
        expect[dsth].append((t, tie, kind, tuple(data)))

    assert int(q.overflow.sum()) == 0
    assert [int(c) for c in q.count] == [len(expect[h]) for h in range(H)]

    # pop everything from all hosts simultaneously; per-host order must match
    got = {h: [] for h in range(H)}
    for _ in range(max(len(v) for v in expect.values())):
        ev, q = equeue.pop_min(q, jnp.ones((H,), bool))
        for h in range(H):
            if bool(ev.valid[h]):
                got[h].append((int(ev.time[h]), int(ev.tie[h]), int(ev.kind[h]), tuple(int(x) for x in ev.data[h])))
    for h in range(H):
        assert got[h] == sorted(expect[h]), f"host {h}"
    assert int(q.count.sum()) == 0
    assert int(jnp.min(q.time)) == TIME_MAX


def test_batched_push_with_conflicts():
    rng = random.Random(3)
    H, Q, M = 5, 32, 60
    q = equeue.create(H, Q)
    dst = [rng.randrange(H) for _ in range(M)]
    valid = [rng.random() < 0.8 for _ in range(M)]
    evs = _mk_events(rng, M, H)
    ties = [pack_tie(k, s, sq) for (_, k, s, sq, _) in evs]
    q = equeue.push_many(
        q,
        dst=jnp.array(dst, jnp.int32),
        valid=jnp.array(valid),
        time=jnp.array([e[0] for e in evs], jnp.int64),
        tie=jnp.array(ties, jnp.int64),
        kind=jnp.array([e[1] for e in evs], jnp.int32),
        data=jnp.array([e[4] for e in evs], jnp.int32),
    )
    expect = {h: [] for h in range(H)}
    for i in range(M):
        if valid[i]:
            t, k, _, _, d = evs[i]
            expect[dst[i]].append((t, ties[i], k, tuple(d)))
    for h in range(H):
        assert equeue.debug_sorted_events(q, h) == sorted(expect[h])


def test_push_self_and_overflow():
    H, Q = 4, 2
    q = equeue.create(H, Q)
    for i in range(3):  # third push overflows every host
        q = equeue.push_self(
            q,
            valid=jnp.ones((H,), bool),
            time=jnp.full((H,), 10 + i, jnp.int64),
            tie=jnp.array([pack_tie(1, h, i) for h in range(H)], jnp.int64),
            kind=jnp.full((H,), 1, jnp.int32),
            data=jnp.zeros((H, PAYLOAD_LANES), jnp.int32),
        )
    np.testing.assert_array_equal(np.asarray(q.count), 2)
    np.testing.assert_array_equal(np.asarray(q.overflow), 1)


def test_pop_respects_want_mask_and_empty_hosts():
    H, Q = 3, 4
    q = equeue.create(H, Q)
    q = equeue.push_self(
        q,
        valid=jnp.array([True, False, True]),
        time=jnp.array([5, 0, 9], jnp.int64),
        tie=jnp.array([pack_tie(1, h, 0) for h in range(H)], jnp.int64),
        kind=jnp.full((H,), 1, jnp.int32),
        data=jnp.zeros((H, PAYLOAD_LANES), jnp.int32),
    )
    ev, q = equeue.pop_min(q, jnp.array([True, True, False]))
    assert bool(ev.valid[0]) and not bool(ev.valid[1]) and not bool(ev.valid[2])
    assert int(ev.time[0]) == 5
    assert [int(c) for c in q.count] == [0, 0, 1]


def test_push_many_sorted_overflow_never_misroutes():
    """Regression (round-4 advisor, high): when one destination receives
    more than deliver_lanes entries, other hosts' deliveries must be
    unaffected and no entry may land on a wrong host with valid=True —
    overflow is dropped and counted, never misrouted."""
    H, Q, D = 4, 16, 2
    q = equeue.create(H, Q)
    # 4 entries to host 1 (two beyond D), 2 to host 0, 1 to host 3;
    # m=7 <= H*D=8, the exact regime the advisor flagged
    dst = [1, 1, 0, 1, 1, 0, 3]
    evs = _mk_events(random.Random(11), len(dst), H)
    ties = [pack_tie(k, s, sq) for (_, k, s, sq, _) in evs]
    q = equeue.push_many_sorted(
        q,
        dst=jnp.array(dst, jnp.int32),
        valid=jnp.ones((len(dst),), bool),
        time=jnp.array([e[0] for e in evs], jnp.int64),
        tie=jnp.array(ties, jnp.int64),
        kind=jnp.array([e[1] for e in evs], jnp.int32),
        data=jnp.array([e[4] for e in evs], jnp.int32),
        deliver_lanes=D,
    )
    sent = {h: [] for h in range(H)}
    for i, d in enumerate(dst):
        t, k, _, _, payload = evs[i]
        sent[d].append((t, ties[i], k, tuple(payload)))
    total_delivered = 0
    for h in range(H):
        got = equeue.debug_sorted_events(q, h)
        # every delivered event must be one this host was actually sent
        for item in got:
            assert item in sent[h], f"host {h} received a misrouted event {item}"
        total_delivered += len(got)
    # hosts within their lane budget receive everything, even while
    # another destination overflows
    assert len(equeue.debug_sorted_events(q, 0)) == 2
    assert len(equeue.debug_sorted_events(q, 3)) == 1
    # host 1 keeps exactly D of its 4 (arrival order); the rest are loud
    assert len(equeue.debug_sorted_events(q, 1)) == D
    assert int(jnp.sum(q.overflow)) == len(dst) - total_delivered == 2


def test_push_many_sorted_overflow_m_gt_grid_property():
    """The repair path's other static regime: m > H*D (no padding; filler
    slack comes only from invalid entries). Deliveries must equal exactly
    the first D entries per destination in arrival order."""
    rng = random.Random(23)
    H, Q, D, M = 3, 64, 2, 20
    for trial in range(8):
        q = equeue.create(H, Q)
        dst = [rng.randrange(H) for _ in range(M)]
        valid = [rng.random() < 0.7 for _ in range(M)]
        evs = _mk_events(rng, M, H, seq_base=trial * M)
        ties = [pack_tie(k, s, sq) for (_, k, s, sq, _) in evs]
        q = equeue.push_many_sorted(
            q,
            dst=jnp.array(dst, jnp.int32),
            valid=jnp.array(valid),
            time=jnp.array([e[0] for e in evs], jnp.int64),
            tie=jnp.array(ties, jnp.int64),
            kind=jnp.array([e[1] for e in evs], jnp.int32),
            data=jnp.array([e[4] for e in evs], jnp.int32),
            deliver_lanes=D,
        )
        sent = {h: [] for h in range(H)}
        for i in range(M):
            if valid[i]:
                t, k, _, _, payload = evs[i]
                sent[dst[i]].append((t, ties[i], k, tuple(payload)))
        delivered = 0
        for h in range(H):
            got = equeue.debug_sorted_events(q, h)
            # multiset/order-exact: the first D arrivals for h, sorted
            assert got == sorted(sent[h][:D]), f"trial {trial} host {h}"
            delivered += len(got)
        n_sent = sum(len(v) for v in sent.values())
        assert int(jnp.sum(q.overflow)) == n_sent - delivered


def test_push_at_time_max_rejected_loudly():
    """The TIME_MAX free-slot invariant: a push at the sentinel time is
    rejected and counted into overflow instead of desyncing occupancy."""
    H, Q = 2, 4
    q = equeue.create(H, Q)
    q = equeue.push_self(
        q,
        valid=jnp.array([True, True]),
        time=jnp.array([5, TIME_MAX], jnp.int64),
        tie=jnp.array([pack_tie(1, h, 0) for h in range(H)], jnp.int64),
        kind=jnp.full((H,), 1, jnp.int32),
        data=jnp.zeros((H, PAYLOAD_LANES), jnp.int32),
    )
    assert [int(c) for c in q.count] == [1, 0]
    assert [int(o) for o in q.overflow] == [0, 1]
    # occupancy stays consistent: free slots == capacity - count
    free = np.asarray(q.time) == TIME_MAX
    assert free.sum(axis=1).tolist() == [Q - 1, Q]
