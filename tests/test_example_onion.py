"""The onion example end-to-end through the real CLI path (`shadow-tpu
run examples/onion/onion.yaml`). The tier-1 smoke is the --replicas 2
ensemble rung (it exercises the whole CLI/Manager/EnsembleRunner path
AND the aggregate block for one XLA compile); the single-run rung lives
in the full tier."""

import json
import pathlib

import pytest
import yaml

from shadow_tpu.runtime.cli_run import run_from_config

pytestmark = pytest.mark.workload

EX = pathlib.Path(__file__).parent.parent / "examples" / "onion" / "onion.yaml"


def _example_config(tmp_path, **overrides):
    raw = yaml.safe_load(EX.read_text())
    raw["general"]["data_directory"] = str(tmp_path / "data")
    raw["general"].update(overrides)
    cfg = tmp_path / "onion.yaml"
    cfg.write_text(yaml.safe_dump(raw))
    return cfg


def test_onion_example_runs(tmp_path):
    cfg = _example_config(tmp_path)
    assert run_from_config(str(cfg)) == 0
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    assert stats["scheduler"] == "tpu"
    assert stats["num_hosts"] == 11
    assert stats["events_handled"] > 0
    assert stats["packets_unroutable"] == 0


def test_onion_example_replicas_aggregate(tmp_path):
    cfg = _example_config(tmp_path, stop_time="300 ms")
    assert run_from_config(str(cfg), replicas=2, replica_seed_stride=5) == 0
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    ens = stats["ensemble"]
    assert ens["replicas"] == 2
    assert len(ens["per_replica"]) == 2
    seeds = [r["seed"] for r in ens["per_replica"]]
    assert seeds == [7, 12]  # seed + r * stride
    assert all(r["events_handled"] > 0 for r in ens["per_replica"])
    assert ens["aggregate"]["events_handled"]["mean"] > 0
