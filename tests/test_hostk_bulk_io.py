"""Bulk-memory IO tier (round-4 verdict Next #5; reference
memory_copier.rs:64-170): payload-bearing stream IO on virtual fds
copies guest memory directly via process_vm_readv/writev — one IPC round
trip per guest syscall — instead of riding the 64 KB shm channel chunk
by chunk. A 64 MB checksummed pipe stream (parent -> forked child, both
ends virtual fds, reads issued with >128 KB buffers so both the write-
and read-bulk paths engage) must arrive intact and beat the chunked shm
path on wall time. experimental.use_memory_manager=false answers
-ENOSYS and the shim falls back — both paths stay available, payloads
identical either way."""

import json
import os
import time

import pytest

from shadow_tpu.runtime.cli_run import run_from_config

PY = "/usr/bin/python3"
pytestmark = pytest.mark.skipif(
    not os.access(PY, os.X_OK), reason="system python3 missing"
)

MB = 64

GUEST = f"""
import hashlib, os, sys
N = {MB} * 1024 * 1024
data = bytes(range(256)) * (N // 256)
r, w = os.pipe()
pid = os.fork()
if pid == 0:
    os.close(w)
    h = hashlib.md5(); total = 0
    while True:
        chunk = os.read(r, 4 * 1024 * 1024)   # > bulk threshold
        if not chunk:
            break
        h.update(chunk); total += len(chunk)
    print("got", total, h.hexdigest())
    sys.stdout.flush()
    os._exit(0)
os.close(r)
total = 0
mv = memoryview(data)
while total < N:
    total += os.write(w, mv[total:])          # 64 MB: bulk path
os.close(w)
os.waitpid(pid, 0)
print("sent", total, hashlib.md5(data).hexdigest())
"""

CONFIG = """
general:
  stop_time: 10 s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: 1_gbit_switch
experimental:
  use_memory_manager: {bulk}
hosts:
  h1:
    network_node_id: 0
    processes:
      - path: {py}
        args: ["-u", "{guest_py}"]
"""


def _run(tmp_path, sub, bulk):
    d = tmp_path / sub
    d.mkdir(parents=True)
    (d / "guest.py").write_text(GUEST)
    cfg = d / "shadow.yaml"
    cfg.write_text(
        CONFIG.format(
            data_dir=d / "data", py=PY, bulk=str(bulk).lower(),
            guest_py=d / "guest.py",
        )
    )
    t0 = time.perf_counter()
    rc = run_from_config(str(cfg))
    wall = time.perf_counter() - t0
    out = next((d / "data" / "h1").glob("*.stdout")).read_text().split()
    stats = json.loads((d / "data" / "sim-stats.json").read_text())
    return rc, out, stats, wall


def test_bulk_pipe_stream_integrity_and_speed(tmp_path):
    n = MB * 1024 * 1024
    rc, out, stats, wall_bulk = _run(tmp_path, "bulk", True)
    assert rc == 0
    # child prints first (EOF), parent after reaping
    assert out[0] == "got" and out[1] == str(n)
    assert out[3] == "sent" and out[4] == str(n)
    assert out[2] == out[5]  # md5 end to end through guest memory copies
    # the 64 MB rode as bulk calls, not ~2000 chunked shm round trips
    assert stats["syscall_counts"].get("write", 0) < 300, stats["syscall_counts"]

    rc2, out2, stats2, wall_chunk = _run(tmp_path, "chunked", False)
    assert rc2 == 0
    assert out2[2] == out[2] and out2[5] == out[5]  # identical payload
    assert stats2["syscall_counts"].get("write", 0) > 900  # shm fallback ran
    # Published throughput (PARITY round-5): the structural effect is the
    # IPC/copy collapse asserted above (~65 vs ~2000 channel round trips
    # for 64 MB); on a 1-core box wall time is dominated by the serial
    # kernel's waiter machinery either way, so the wall ratio is
    # informational, not asserted.
    print(
        f"\nbulk-io 64MB pipe: bulk {n / wall_bulk / 1e6:.0f} MB/s wall, "
        f"chunked {n / wall_chunk / 1e6:.0f} MB/s wall"
    )
