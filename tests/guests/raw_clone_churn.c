/* Raw-thread churn guest (ADVICE r3 regression): creates and joins more
 * raw clone(CLONE_THREAD) threads than the shim's slot table holds
 * (RAW_THREADS_MAX = 128). Before the fix, exited slots were retired
 * with rtid=-1 — a value the allocator CAS (which claims rtid==0) never
 * reuses — so creation #129 died child-side with exit(119) after the
 * parent already got a vtid, hanging the simulation on a THREAD_START
 * that never arrives. */
#define _GNU_SOURCE
#include <sched.h>
#include <stdio.h>
#include <sys/mman.h>
#include <unistd.h>

static long rsys(long nr, long a1, long a2, long a3, long a4, long a5) {
    long ret;
    register long r10 asm("r10") = a4;
    register long r8 asm("r8") = a5;
    asm volatile("syscall"
                 : "=a"(ret)
                 : "0"(nr), "D"(a1), "S"(a2), "d"(a3), "r"(r10), "r"(r8)
                 : "rcx", "r11", "memory");
    return ret;
}

#define SYS_futex_ 202
#define FUTEX_WAIT_ 0
#define FUTEX_WAKE_ 1

#define ROUNDS 140 /* > RAW_THREADS_MAX */

static volatile int g_flag;
static volatile int g_count;

static int child_fn(void *arg) {
    (void)arg;
    g_count++;
    g_flag = 1;
    rsys(SYS_futex_, (long)&g_flag, FUTEX_WAKE_, 1, 0, 0);
    return 0;
}

static long my_clone(int (*fn)(void *), void *stack_top, void *arg) {
    void **sp = (void **)stack_top;
    *--sp = arg;
    *--sp = (void *)fn;
    long flags = CLONE_VM | CLONE_FS | CLONE_FILES | CLONE_SIGHAND |
                 CLONE_THREAD | CLONE_SYSVSEM;
    long ret;
    asm volatile("syscall\n\t"
                 "test %%rax, %%rax\n\t"
                 "jnz 1f\n\t"
                 "pop %%rax\n\t"
                 "pop %%rdi\n\t"
                 "call *%%rax\n\t"
                 "mov %%rax, %%rdi\n\t"
                 "mov $60, %%rax\n\t"
                 "syscall\n\t"
                 "1:"
                 : "=a"(ret)
                 : "0"(56L), "D"(flags), "S"(sp), "d"(0)
                 : "rcx", "r11", "memory");
    return ret;
}

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0);
    for (int i = 0; i < ROUNDS; i++) {
        /* fresh stack per thread (leaked): the exiting child still runs
         * its seccomp-trapped exit path on this stack after the join
         * wake, so the stack cannot be reused for the next thread */
        void *stk = mmap(NULL, 64 * 1024, PROT_READ | PROT_WRITE,
                         MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
        if (stk == MAP_FAILED)
            return 1;
        g_flag = 0;
        long tid = my_clone(child_fn, (char *)stk + 64 * 1024, 0);
        if (tid < 0) {
            printf("clone %d failed %ld\n", i, tid);
            return 1;
        }
        while (!g_flag)
            rsys(SYS_futex_, (long)&g_flag, FUTEX_WAIT_, 0, 0, 0);
    }
    printf("churn ok %d\n", g_count);
    return 0;
}
