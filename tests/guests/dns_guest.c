/* Guest test program: name-service APIs under the shim.
 * Usage: dns_guest <peer_hostname> <peer_ip_dotted> <own_ip_dotted>
 * Exercises getaddrinfo, gethostbyname, getnameinfo (forward+reverse),
 * getifaddrs, gethostname. */
#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s\n", name);                                         \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

int main(int argc, char **argv) {
    if (argc < 4)
        return 2;
    const char *peer = argv[1], *peer_ip = argv[2], *own_ip = argv[3];

    struct addrinfo hints, *res = NULL;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_DGRAM;
    CHECK(getaddrinfo(peer, "7000", &hints, &res) == 0 && res, "getaddrinfo");
    char dotted[64];
    struct sockaddr_in *sin = (struct sockaddr_in *)res->ai_addr;
    inet_ntop(AF_INET, &sin->sin_addr, dotted, sizeof(dotted));
    CHECK(strcmp(dotted, peer_ip) == 0, "getaddrinfo-ip");
    CHECK(ntohs(sin->sin_port) == 7000, "getaddrinfo-port");
    CHECK(res->ai_socktype == SOCK_DGRAM, "getaddrinfo-socktype");

    /* reverse: ip -> name */
    char hostbuf[256], servbuf[32];
    CHECK(getnameinfo((struct sockaddr *)sin, sizeof(*sin), hostbuf,
                      sizeof(hostbuf), servbuf, sizeof(servbuf), 0) == 0,
          "getnameinfo");
    CHECK(strcmp(hostbuf, peer) == 0, "getnameinfo-name");
    CHECK(strcmp(servbuf, "7000") == 0, "getnameinfo-serv");
    CHECK(getnameinfo((struct sockaddr *)sin, sizeof(*sin), hostbuf,
                      sizeof(hostbuf), NULL, 0, NI_NUMERICHOST) == 0 &&
              strcmp(hostbuf, peer_ip) == 0,
          "getnameinfo-numeric");
    freeaddrinfo(res);

    struct hostent *he = gethostbyname(peer);
    CHECK(he && he->h_addrtype == AF_INET, "gethostbyname");
    inet_ntop(AF_INET, he->h_addr_list[0], dotted, sizeof(dotted));
    CHECK(strcmp(dotted, peer_ip) == 0, "gethostbyname-ip");

    /* interfaces: lo + eth0 with our simulated address */
    struct ifaddrs *ifa = NULL;
    CHECK(getifaddrs(&ifa) == 0 && ifa, "getifaddrs");
    int saw_lo = 0, saw_eth = 0;
    for (struct ifaddrs *i = ifa; i; i = i->ifa_next) {
        if (!i->ifa_addr || i->ifa_addr->sa_family != AF_INET)
            continue;
        struct sockaddr_in *a = (struct sockaddr_in *)i->ifa_addr;
        inet_ntop(AF_INET, &a->sin_addr, dotted, sizeof(dotted));
        if (strcmp(i->ifa_name, "lo") == 0 && strcmp(dotted, "127.0.0.1") == 0)
            saw_lo = 1;
        if (strcmp(i->ifa_name, "eth0") == 0 && strcmp(dotted, own_ip) == 0)
            saw_eth = 1;
    }
    freeifaddrs(ifa);
    CHECK(saw_lo, "ifaddrs-lo");
    CHECK(saw_eth, "ifaddrs-eth0");

    char hn[256];
    CHECK(gethostname(hn, sizeof(hn)) == 0 && strlen(hn) > 0, "gethostname");
    printf("hostname=%s\n", hn);
    printf("dns all ok\n");
    return 0;
}
