/* Raw-futex guest: exercises the kernel's SYS_futex emulation directly
 * (syscall(SYS_futex, ...)) and through glibc semaphores (sem_wait/post
 * issue raw futex, not interposed pthread symbols), plus a WAIT timeout
 * and a raw fork-style clone. Prints sim-time measurements so the test
 * can assert both semantics and determinism. */
#define _GNU_SOURCE
#include <errno.h>
#include <linux/futex.h>
#include <pthread.h>
#include <semaphore.h>
#include <stdint.h>
#include <stdio.h>
#include <sys/syscall.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static uint32_t word = 0;
static sem_t sem_a, sem_b;
static long pings = 0;

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000 + ts.tv_nsec;
}

static long futex(uint32_t *uaddr, int op, uint32_t val,
                  const struct timespec *ts) {
    return syscall(SYS_futex, uaddr, op, val, ts, NULL, 0);
}

static void *waiter_thread(void *arg) {
    (void)arg;
    int64_t t0 = now_ns();
    long r = futex(&word, FUTEX_WAIT, 0, NULL);
    int64_t waited = now_ns() - t0;
    printf("futex_wait ret=%ld val=%u waited_ms=%lld\n", r, word,
           (long long)(waited / 1000000));
    return NULL;
}

static void *pong_thread(void *arg) {
    (void)arg;
    for (int i = 0; i < 5; i++) {
        sem_wait(&sem_a);
        pings++;
        sem_post(&sem_b);
    }
    return NULL;
}

int main(void) {
    /* 1. raw FUTEX_WAIT/WAKE across threads with simulated sleep */
    pthread_t th;
    pthread_create(&th, NULL, waiter_thread, NULL);
    struct timespec d = {0, 50 * 1000000}; /* 50 ms sim */
    nanosleep(&d, NULL);
    __atomic_store_n(&word, 7, __ATOMIC_SEQ_CST);
    long woken = futex(&word, FUTEX_WAKE, 1, NULL);
    pthread_join(th, NULL);
    printf("woken=%ld\n", woken);

    /* 2. glibc semaphore ping-pong (sem_wait/post -> raw futex) */
    sem_init(&sem_a, 0, 0);
    sem_init(&sem_b, 0, 0);
    pthread_t pp;
    pthread_create(&pp, NULL, pong_thread, NULL);
    for (int i = 0; i < 5; i++) {
        sem_post(&sem_a);
        sem_wait(&sem_b);
    }
    pthread_join(pp, NULL);
    printf("pings=%ld\n", pings);

    /* 3. FUTEX_WAIT with a relative timeout: must time out on sim time */
    uint32_t never = 0;
    struct timespec to = {0, 30 * 1000000}; /* 30 ms */
    int64_t t0 = now_ns();
    long r = futex(&never, FUTEX_WAIT, 0, &to);
    int64_t waited = now_ns() - t0;
    printf("timeout ret=%ld errno_ok=%d waited_ms=%lld\n", r,
           r == -1 && errno == ETIMEDOUT, (long long)(waited / 1000000));

    /* 4. value-mismatch fast path: EAGAIN without blocking */
    uint32_t eleven = 11;
    r = futex(&eleven, FUTEX_WAIT, 12, NULL);
    printf("eagain ret=%ld errno_ok=%d\n", r, r == -1 && errno == EAGAIN);

    /* 5. raw fork-style clone (what glibc fork() emits) routes into the
     * managed fork path: the child must be simulated, not escaped */
    long child = syscall(SYS_clone, (long)SIGCHLD, 0L, 0L, 0L, 0L);
    if (child == 0) {
        printf("clone child pid=%d\n", (int)getpid());
        fflush(stdout);
        _exit(42);
    }
    int status = 0;
    waitpid((pid_t)child, &status, 0);
    printf("clone parent: child=%ld status=%d\n", child > 0 ? 1L : 0L,
           WIFEXITED(status) ? WEXITSTATUS(status) : -1);
    return 0;
}
