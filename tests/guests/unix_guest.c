/* Guest test program: unix-domain sockets within one process.
 * Exercises socketpair, abstract-namespace stream listen/connect/accept,
 * dgram sendto/recvfrom with source addresses, getsockname/getpeername,
 * poll readiness, and EOF on close. Prints "ok <step>" lines; exits 0
 * only if every step passed. */
#include <poll.h>
#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s\n", name);                                         \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

static void abs_addr(struct sockaddr_un *un, socklen_t *len, const char *name) {
    memset(un, 0, sizeof(*un));
    un->sun_family = AF_UNIX;
    un->sun_path[0] = '\0';
    strcpy(un->sun_path + 1, name);
    *len = (socklen_t)(offsetof(struct sockaddr_un, sun_path) + 1 + strlen(name));
}

int main(void) {
    /* --- socketpair ----------------------------------------------------- */
    int sv[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0, "socketpair");
    CHECK(write(sv[0], "hello", 5) == 5, "sp-write");
    char buf[256];
    CHECK(read(sv[1], buf, sizeof(buf)) == 5 && memcmp(buf, "hello", 5) == 0,
          "sp-read");
    CHECK(send(sv[1], "back", 4, 0) == 4, "sp-send");
    CHECK(recv(sv[0], buf, sizeof(buf), 0) == 4 && memcmp(buf, "back", 4) == 0,
          "sp-recv");
    close(sv[1]);
    CHECK(read(sv[0], buf, sizeof(buf)) == 0, "sp-eof");
    close(sv[0]);

    /* --- abstract stream server/client in-process ----------------------- */
    int srv = socket(AF_UNIX, SOCK_STREAM, 0);
    CHECK(srv >= 0, "stream-socket");
    struct sockaddr_un a;
    socklen_t alen;
    abs_addr(&a, &alen, "test-stream");
    CHECK(bind(srv, (struct sockaddr *)&a, alen) == 0, "stream-bind");
    CHECK(listen(srv, 4) == 0, "stream-listen");
    struct sockaddr_un got;
    socklen_t glen = sizeof(got);
    CHECK(getsockname(srv, (struct sockaddr *)&got, &glen) == 0 &&
              got.sun_family == AF_UNIX && got.sun_path[0] == '\0' &&
              strcmp(got.sun_path + 1, "test-stream") == 0,
          "stream-getsockname");

    int cli = socket(AF_UNIX, SOCK_STREAM, 0);
    CHECK(connect(cli, (struct sockaddr *)&a, alen) == 0, "stream-connect");

    struct pollfd p = {.fd = srv, .events = POLLIN};
    CHECK(poll(&p, 1, 0) == 1 && (p.revents & POLLIN), "stream-poll-accept");
    int conn = accept(srv, NULL, NULL);
    CHECK(conn >= 0, "stream-accept");

    glen = sizeof(got);
    CHECK(getpeername(cli, (struct sockaddr *)&got, &glen) == 0 &&
              got.sun_path[0] == '\0' &&
              strcmp(got.sun_path + 1, "test-stream") == 0,
          "stream-getpeername");

    CHECK(send(cli, "ping", 4, 0) == 4, "stream-send");
    CHECK(recv(conn, buf, sizeof(buf), 0) == 4 && memcmp(buf, "ping", 4) == 0,
          "stream-echo-in");
    CHECK(send(conn, "pong", 4, 0) == 4, "stream-reply");
    CHECK(recv(cli, buf, sizeof(buf), 0) == 4 && memcmp(buf, "pong", 4) == 0,
          "stream-echo-out");
    CHECK(shutdown(cli, SHUT_WR) == 0, "stream-shutdown");
    CHECK(recv(conn, buf, sizeof(buf), 0) == 0, "stream-eof-after-shutdown");
    close(conn);
    close(cli);
    close(srv);

    /* connect to a closed listener must be refused */
    int cli2 = socket(AF_UNIX, SOCK_STREAM, 0);
    CHECK(connect(cli2, (struct sockaddr *)&a, alen) < 0, "stream-refused");
    close(cli2);

    /* --- dgram with addresses ------------------------------------------- */
    int d1 = socket(AF_UNIX, SOCK_DGRAM, 0);
    int d2 = socket(AF_UNIX, SOCK_DGRAM, 0);
    struct sockaddr_un a1, a2;
    socklen_t l1, l2;
    abs_addr(&a1, &l1, "dg-one");
    abs_addr(&a2, &l2, "dg-two");
    CHECK(bind(d1, (struct sockaddr *)&a1, l1) == 0, "dgram-bind1");
    CHECK(bind(d2, (struct sockaddr *)&a2, l2) == 0, "dgram-bind2");
    CHECK(bind(d2, (struct sockaddr *)&a2, l2) < 0, "dgram-rebind-einval");
    CHECK(sendto(d1, "dgram!", 6, 0, (struct sockaddr *)&a2, l2) == 6,
          "dgram-sendto");
    struct sockaddr_un src;
    socklen_t slen = sizeof(src);
    ssize_t r = recvfrom(d2, buf, sizeof(buf), 0, (struct sockaddr *)&src, &slen);
    CHECK(r == 6 && memcmp(buf, "dgram!", 6) == 0, "dgram-recv");
    CHECK(src.sun_family == AF_UNIX && src.sun_path[0] == '\0' &&
              strcmp(src.sun_path + 1, "dg-one") == 0,
          "dgram-srcaddr");
    /* connected dgram */
    CHECK(connect(d2, (struct sockaddr *)&a1, l1) == 0, "dgram-connect");
    CHECK(send(d2, "reply", 5, 0) == 5, "dgram-send-connected");
    CHECK(recv(d1, buf, sizeof(buf), 0) == 5 && memcmp(buf, "reply", 5) == 0,
          "dgram-recv-connected");
    close(d1);
    close(d2);

    printf("unix all ok\n");
    return 0;
}
