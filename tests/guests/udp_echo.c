/* Guest test program: UDP echo server. Usage: udp_echo <port> <n_echoes>
 * The managed-process analogue of the reference's paired socket tests
 * (reference: src/test/socket_utils.rs patterns). Runs under the shim. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc < 3)
        return 2;
    int port = atoi(argv[1]);
    int n = atoi(argv[2]);
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0)
        return 3;
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((unsigned short)port);
    if (bind(fd, (struct sockaddr *)&addr, sizeof(addr)) != 0)
        return 4;
    char buf[4096];
    for (int i = 0; i < n; i++) {
        struct sockaddr_in src;
        socklen_t slen = sizeof(src);
        ssize_t r = recvfrom(fd, buf, sizeof(buf), 0, (struct sockaddr *)&src,
                             &slen);
        if (r < 0)
            return 5;
        struct timespec ts;
        clock_gettime(CLOCK_REALTIME, &ts);
        printf("echo %d len=%zd t=%lld.%09ld\n", i, r, (long long)ts.tv_sec,
               ts.tv_nsec);
        sendto(fd, buf, (size_t)r, 0, (struct sockaddr *)&src, slen);
    }
    close(fd);
    printf("server done pid=%d\n", (int)getpid());
    return 0;
}
