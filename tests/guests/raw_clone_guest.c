/* Raw clone(CLONE_THREAD) guest: creates a thread the musl way — raw
 * clone syscall with a self-managed stack, no glibc pthreads anywhere —
 * then synchronizes with raw futexes and joins via a flag. The adoption
 * trampoline (shim.c raw_thread_clone) must attach the child to the
 * simulation: its raw syscalls (write, futex, nanosleep, exit) are
 * simulated and deterministically scheduled.
 * (reference: managed_thread.rs:294-365 native_clone + src/test/golang/
 * as the eventual runtime target) */
#define _GNU_SOURCE
#include <sched.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

static long rsys(long nr, long a1, long a2, long a3, long a4, long a5) {
    long ret;
    register long r10 asm("r10") = a4;
    register long r8 asm("r8") = a5;
    asm volatile("syscall"
                 : "=a"(ret)
                 : "0"(nr), "D"(a1), "S"(a2), "d"(a3), "r"(r10), "r"(r8)
                 : "rcx", "r11", "memory");
    return ret;
}

#define SYS_write_ 1
#define SYS_nanosleep_ 35
#define SYS_futex_ 202
#define SYS_exit_ 60
#define SYS_clone_ 56
#define FUTEX_WAIT_ 0
#define FUTEX_WAKE_ 1

static volatile int g_flag = 0;
static volatile int g_sum = 0;

static int child_fn(void *arg) {
    long n = (long)arg;
    struct { long s, ns; } d = {0, 20 * 1000 * 1000};
    rsys(SYS_nanosleep_, (long)&d, 0, 0, 0, 0); /* 20 ms simulated */
    g_sum = (int)(n * 7);
    const char msg[] = "child ran\n";
    rsys(SYS_write_, 1, (long)msg, sizeof(msg) - 1, 0, 0);
    g_flag = 1;
    rsys(SYS_futex_, (long)&g_flag, FUTEX_WAKE_, 1, 0, 0);
    return 0;
}

static long my_clone(int (*fn)(void *), void *stack_top, void *arg) {
    void **sp = (void **)stack_top;
    *--sp = arg;
    *--sp = (void *)fn;
    long flags = CLONE_VM | CLONE_FS | CLONE_FILES | CLONE_SIGHAND |
                 CLONE_THREAD | CLONE_SYSVSEM;
    long ret;
    asm volatile("syscall\n\t"
                 "test %%rax, %%rax\n\t"
                 "jnz 1f\n\t"
                 /* child: pop fn and arg from our prepared stack */
                 "pop %%rax\n\t"
                 "pop %%rdi\n\t"
                 "call *%%rax\n\t"
                 "mov %%rax, %%rdi\n\t"
                 "mov $60, %%rax\n\t"
                 "syscall\n\t"
                 "1:"
                 : "=a"(ret)
                 : "0"((long)SYS_clone_), "D"(flags), "S"(sp), "d"(0)
                 : "rcx", "r11", "memory");
    return ret;
}

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0);
    void *stk = mmap(NULL, 256 * 1024, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
    if (stk == MAP_FAILED) {
        perror("mmap");
        return 1;
    }
    long tid = my_clone(child_fn, (char *)stk + 256 * 1024, (void *)6L);
    if (tid < 0) {
        printf("clone failed %ld\n", tid);
        return 1;
    }
    printf("cloned tid>0: %d\n", tid > 0);
    while (!g_flag) /* futex join on our own flag */
        rsys(SYS_futex_, (long)&g_flag, FUTEX_WAIT_, 0, 0, 0);
    printf("sum %d\n", g_sum);
    printf("raw clone all ok\n");
    return 0;
}
