/* Guest test program: MSG_WAITALL over simulated TCP loopback. A writer
 * thread sends 30000 bytes in paced chunks; the reader's single
 * recv(MSG_WAITALL) must return the full count. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <pthread.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define TOTAL 100000 /* > SHIM_BUF_SIZE: exercises the shim's multi-round loop */

static void *writer(void *arg) {
    int fd = *(int *)arg;
    char chunk[10000];
    memset(chunk, 'x', sizeof(chunk));
    for (int i = 0; i < TOTAL / 10000; i++) {
        struct timespec d = {0, 20000000};
        nanosleep(&d, NULL);
        ssize_t off = 0;
        while (off < (ssize_t)sizeof(chunk)) {
            ssize_t w = send(fd, chunk + off, sizeof(chunk) - off, 0);
            if (w <= 0)
                return (void *)1;
            off += w;
        }
    }
    return NULL;
}

int main(void) {
    int srv = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a;
    memset(&a, 0, sizeof(a));
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = 0; /* ephemeral: no collisions in the native pairing run */
    if (bind(srv, (struct sockaddr *)&a, sizeof(a)) || listen(srv, 1))
        return 2;
    socklen_t alen = sizeof(a);
    if (getsockname(srv, (struct sockaddr *)&a, &alen))
        return 2;
    int cli = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in dst = a;
    dst.sin_addr.s_addr = htonl(0x7F000001);
    if (connect(cli, (struct sockaddr *)&dst, sizeof(dst)))
        return 3;
    int conn = accept(srv, NULL, NULL);
    if (conn < 0)
        return 4;

    pthread_t w;
    pthread_create(&w, NULL, writer, &cli);

    static char buf[TOTAL + 16];
    ssize_t r = recv(conn, buf, TOTAL, MSG_WAITALL);
    pthread_join(w, NULL);
    if (r != TOTAL) {
        printf("FAIL waitall got %zd\n", r);
        return 5;
    }
    /* after the writer closes, WAITALL returns the short remainder */
    close(cli);
    r = recv(conn, buf, 1000, MSG_WAITALL);
    if (r != 0) {
        printf("FAIL waitall-eof got %zd\n", r);
        return 6;
    }
    printf("waitall ok\n");
    return 0;
}
