/* Guest test program: pthreads under the shim — create/join with return
 * values, mutex-protected shared counter, condvar producer/consumer,
 * cond_timedwait timeout on simulated time. */
#define _GNU_SOURCE
#include <errno.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s (errno=%d)\n", name, errno);                       \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

/* --- shared counter under a mutex ------------------------------------- */

static pthread_mutex_t g_mu = PTHREAD_MUTEX_INITIALIZER;
static long g_counter = 0;

static void *bump(void *arg) {
    long n = (long)(intptr_t)arg;
    for (long i = 0; i < n; i++) {
        pthread_mutex_lock(&g_mu);
        g_counter++;
        pthread_mutex_unlock(&g_mu);
    }
    return (void *)(intptr_t)(n * 10);
}

/* --- producer/consumer over a condvar --------------------------------- */

static pthread_mutex_t q_mu = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t q_cv = PTHREAD_COND_INITIALIZER;
static int q_items = 0, q_consumed = 0, q_done = 0;

static void *producer(void *arg) {
    (void)arg;
    for (int i = 0; i < 5; i++) {
        struct timespec d = {0, 10000000}; /* 10ms cadence */
        nanosleep(&d, NULL);
        pthread_mutex_lock(&q_mu);
        q_items++;
        pthread_cond_signal(&q_cv);
        pthread_mutex_unlock(&q_mu);
    }
    pthread_mutex_lock(&q_mu);
    q_done = 1;
    pthread_cond_broadcast(&q_cv);
    pthread_mutex_unlock(&q_mu);
    return NULL;
}

static void *consumer(void *arg) {
    (void)arg;
    pthread_mutex_lock(&q_mu);
    for (;;) {
        while (q_items == 0 && !q_done)
            pthread_cond_wait(&q_cv, &q_mu);
        if (q_items > 0) {
            q_items--;
            q_consumed++;
        } else if (q_done) {
            break;
        }
    }
    pthread_mutex_unlock(&q_mu);
    return NULL;
}

static void *exiter(void *arg) {
    (void)arg;
    pthread_exit((void *)(intptr_t)777); /* exit without returning */
}

static void *late_worker(void *arg) {
    (void)arg;
    struct timespec d = {0, 50000000};
    nanosleep(&d, NULL);
    printf("worker outlived main\n");
    fflush(stdout);
    return NULL;
}

int main(int argc, char **argv) {
    if (argc > 1 && strcmp(argv[1], "mainexit") == 0) {
        /* main pthread_exits while a worker still runs (POSIX) */
        pthread_t w;
        if (pthread_create(&w, NULL, late_worker, NULL) != 0)
            return 1;
        printf("main exiting early\n");
        fflush(stdout);
        pthread_exit(NULL);
    }
    /* pthread_exit path */
    pthread_t e;
    CHECK(pthread_create(&e, NULL, exiter, NULL) == 0, "create-exiter");
    void *re = NULL;
    CHECK(pthread_join(e, &re) == 0 && (intptr_t)re == 777, "pthread-exit-retval");

    /* create/join with retvals; mutex protects the counter */
    pthread_t a, b;
    CHECK(pthread_create(&a, NULL, bump, (void *)(intptr_t)1000) == 0,
          "create-a");
    CHECK(pthread_create(&b, NULL, bump, (void *)(intptr_t)500) == 0,
          "create-b");
    void *ra = NULL, *rb = NULL;
    CHECK(pthread_join(a, &ra) == 0, "join-a");
    CHECK(pthread_join(b, &rb) == 0, "join-b");
    CHECK((intptr_t)ra == 10000 && (intptr_t)rb == 5000, "join-retvals");
    CHECK(g_counter == 1500, "mutex-counter");

    /* trylock semantics */
    CHECK(pthread_mutex_trylock(&g_mu) == 0, "trylock");
    CHECK(pthread_mutex_unlock(&g_mu) == 0, "trylock-unlock");

    /* recursive mutex: same thread may relock */
    static pthread_mutex_t rec = PTHREAD_RECURSIVE_MUTEX_INITIALIZER_NP;
    CHECK(pthread_mutex_lock(&rec) == 0, "recursive-lock1");
    CHECK(pthread_mutex_lock(&rec) == 0, "recursive-lock2");
    CHECK(pthread_mutex_unlock(&rec) == 0, "recursive-unlock1");
    CHECK(pthread_mutex_unlock(&rec) == 0, "recursive-unlock2");

    /* producer/consumer */
    pthread_t p, c;
    CHECK(pthread_create(&c, NULL, consumer, NULL) == 0, "create-consumer");
    CHECK(pthread_create(&p, NULL, producer, NULL) == 0, "create-producer");
    CHECK(pthread_join(p, NULL) == 0, "join-producer");
    CHECK(pthread_join(c, NULL) == 0, "join-consumer");
    CHECK(q_consumed == 5, "condvar-consumed");

    /* cond_timedwait times out on simulated time */
    pthread_mutex_lock(&q_mu);
    long long t0 = now_ns();
    struct timespec abst;
    clock_gettime(CLOCK_REALTIME, &abst);
    abst.tv_nsec += 200000000; /* +200ms */
    if (abst.tv_nsec >= 1000000000) {
        abst.tv_sec++;
        abst.tv_nsec -= 1000000000;
    }
    int rc = pthread_cond_timedwait(&q_cv, &q_mu, &abst);
    long long waited = now_ns() - t0;
    pthread_mutex_unlock(&q_mu);
    CHECK(rc == ETIMEDOUT, "timedwait-etimedout");
    CHECK(waited >= 190000000LL && waited <= 400000000LL, "timedwait-timing");

    printf("threads all ok counter=%ld consumed=%d\n", g_counter, q_consumed);
    return 0;
}
