/* Memory-map + large-IO breadth guest (reference roles:
 * memory_manager/mod.rs bookkeeping + regular-file mmap; the >64KB
 * transfers exercise the shim's chunked write/writev, which must be
 * invisible to the guest). Prints deterministic checksums — a native run
 * and a shadow run must produce identical stdout.
 * Usage: mm_guest */
#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/wait.h>
#include <unistd.h>

static uint64_t fnv(const unsigned char *p, size_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

#define BIG (300 * 1024)

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0); /* forks must not replay the buffer */
    /* 1. anonymous mmap: 1 MB, fill, checksum, unmap */
    size_t alen = 1 << 20;
    unsigned char *a = mmap(NULL, alen, PROT_READ | PROT_WRITE,
                            MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (a == MAP_FAILED) {
        perror("mmap anon");
        return 1;
    }
    for (size_t i = 0; i < alen; i++)
        a[i] = (unsigned char)(i * 7 + 3);
    printf("anon %llx\n", (unsigned long long)fnv(a, alen));
    if (munmap(a, alen) != 0) {
        perror("munmap");
        return 1;
    }

    /* 2. file-backed mmap of a sandbox file (written natively first) */
    size_t flen = 256 * 1024;
    int fd = open("mmfile.bin", O_CREAT | O_RDWR | O_TRUNC, 0644);
    if (fd < 0) {
        perror("open");
        return 1;
    }
    unsigned char *tmp = malloc(flen);
    for (size_t i = 0; i < flen; i++)
        tmp[i] = (unsigned char)(i ^ (i >> 8));
    size_t off = 0;
    while (off < flen) {
        ssize_t w = write(fd, tmp + off, flen - off);
        if (w <= 0) {
            perror("file write");
            return 1;
        }
        off += (size_t)w;
    }
    unsigned char *m = mmap(NULL, flen, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
        perror("mmap file");
        return 1;
    }
    close(fd);
    printf("file %llx %s\n", (unsigned long long)fnv(m, flen),
           memcmp(m, tmp, flen) == 0 ? "match" : "MISMATCH");
    /* keep `m` mapped so the kernel ledger has a live region at exit */

    /* 3. grow the break and touch it */
    unsigned char *b = sbrk(64 * 1024);
    if (b == (void *)-1) {
        perror("sbrk");
        return 1;
    }
    memset(b, 0x5a, 64 * 1024);
    printf("brk %llx\n", (unsigned long long)fnv(b, 64 * 1024));

    /* 4. one write() of 300 KB through a pipe (fork: child drains) */
    int pfd[2], rfd[2];
    if (pipe(pfd) != 0 || pipe(rfd) != 0) {
        perror("pipe");
        return 1;
    }
    unsigned char *big = malloc(BIG);
    for (size_t i = 0; i < BIG; i++)
        big[i] = (unsigned char)(i * 13 + 1);
    pid_t pid = fork();
    if (pid < 0) {
        perror("fork");
        return 1;
    }
    if (pid == 0) { /* child: drain the pipe, reply with checksum */
        close(pfd[1]);
        close(rfd[0]);
        unsigned char *rb = malloc(BIG);
        size_t got = 0;
        while (got < BIG) {
            ssize_t r = read(pfd[0], rb + got, BIG - got);
            if (r <= 0)
                break;
            got += (size_t)r;
        }
        uint64_t h = fnv(rb, got);
        char line[64];
        int n = snprintf(line, sizeof(line), "%zu %llx", got,
                         (unsigned long long)h);
        if (write(rfd[1], line, (size_t)n) != n)
            _exit(3);
        _exit(0);
    }
    close(pfd[0]);
    close(rfd[1]);
    ssize_t w = write(pfd[1], big, BIG); /* ONE call, > shim buffer */
    printf("pipe wrote %zd\n", w);
    close(pfd[1]);
    char line[64];
    ssize_t r = read(rfd[0], line, sizeof(line) - 1);
    if (r < 0) {
        perror("reply read");
        return 1;
    }
    line[r] = 0;
    printf("pipe child %s (want %llx)\n", line, (unsigned long long)fnv(big, BIG));
    int st = 0;
    waitpid(pid, &st, 0);

    /* 5. one writev (3 iovecs, ~200 KB total) over a stream socketpair */
    int sv[2], rfd2[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0 || pipe(rfd2) != 0) {
        perror("socketpair");
        return 1;
    }
    pid_t pid2 = fork();
    if (pid2 == 0) {
        close(sv[0]);
        close(rfd2[0]);
        unsigned char *rb = malloc(BIG);
        size_t got = 0;
        for (;;) {
            ssize_t rr = read(sv[1], rb + got, BIG - got);
            if (rr <= 0)
                break;
            got += (size_t)rr;
        }
        char l2[64];
        int n2 = snprintf(l2, sizeof(l2), "%zu %llx", got,
                          (unsigned long long)fnv(rb, got));
        if (write(rfd2[1], l2, (size_t)n2) != n2)
            _exit(3);
        _exit(0);
    }
    close(sv[1]);
    close(rfd2[1]);
    struct iovec iov[3] = {
        {big, 90 * 1024}, {big + 90 * 1024, 70 * 1024}, {big + 160 * 1024, 40 * 1024},
    };
    ssize_t wv = writev(sv[0], iov, 3);
    printf("sock writev %zd\n", wv);
    close(sv[0]);
    ssize_t r2 = read(rfd2[0], line, sizeof(line) - 1);
    if (r2 < 0) {
        perror("sock reply read");
        return 1;
    }
    line[r2] = 0;
    printf("sock child %s (want %llx)\n", line,
           (unsigned long long)fnv(big, 200 * 1024));
    waitpid(pid2, &st, 0);

    printf("mm all ok\n");
    return 0;
}
