/* Unified-fd-space guest (reference descriptor_table.rs:12 POSIX
 * lowest-free): virtual fds get real lowest-free numbers, interleave
 * correctly with native files, work in select() below FD_SETSIZE, and can
 * be dup2()ed onto stdin (inetd style). Output must match a native run
 * byte for byte — including the fd numbers themselves. */
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0);

    int s1 = socket(AF_INET, SOCK_DGRAM, 0); /* lowest free: 3 */
    int f = open("data.txt", O_CREAT | O_RDWR, 0644); /* native: 4 */
    int s2 = socket(AF_INET, SOCK_DGRAM, 0); /* 5 */
    close(s1);
    int s3 = socket(AF_INET, SOCK_DGRAM, 0); /* reuses 3 */
    printf("fds %d %d %d %d\n", s1, f, s2, s3);

    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
        perror("socketpair");
        return 1;
    }
    printf("pair %d %d\n", sv[0], sv[1]);
    if (write(sv[1], "x", 1) != 1)
        return 1;
    fd_set rf;
    FD_ZERO(&rf);
    FD_SET(sv[0], &rf);
    struct timeval tv = {5, 0};
    int n = select(sv[0] + 1, &rf, NULL, NULL, &tv);
    printf("select %d ready=%d\n", n, FD_ISSET(sv[0], &rf));

    int p[2];
    if (pipe(p) != 0)
        return 1;
    if (write(p[1], "hello", 5) != 5)
        return 1;
    if (dup2(p[0], 0) != 0) { /* redirect stdin to the pipe */
        perror("dup2");
        return 1;
    }
    char buf[8] = {0};
    ssize_t r = read(0, buf, 5);
    printf("stdin %zd %s\n", r, buf);

    printf("fd all ok\n");
    return 0;
}
