/* One-way TCP streaming guest (no echo lockstep, no echo deadlock):
 *   tcp_stream serve <port>                — accept one conn, read to EOF,
 *                                            print bytes + elapsed
 *   tcp_stream send <host> <port> <nbytes> — stream nbytes as fast as the
 *                                            socket accepts, half-close,
 *                                            wait for the peer's EOF
 * Exercises real window/congestion dynamics: the sender is purely
 * window/cwnd-limited, which the chunk-lockstep echo client never is. */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static int serve(int port) {
    int ls = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons((uint16_t)port);
    if (bind(ls, (struct sockaddr *)&a, sizeof(a)) != 0 || listen(ls, 4) != 0) {
        perror("listen");
        return 1;
    }
    int fd = accept(ls, NULL, NULL);
    if (fd < 0) {
        perror("accept");
        return 1;
    }
    int64_t t0 = now_ns();
    char buf[16384];
    long total = 0, errors = 0;
    for (;;) {
        ssize_t r = read(fd, buf, sizeof(buf));
        if (r < 0) {
            perror("read");
            return 1;
        }
        if (r == 0)
            break;
        for (ssize_t i = 0; i < r; i++)
            if (buf[i] != (char)((total + i) % 251))
                errors++;
        total += r;
    }
    int64_t t1 = now_ns();
    printf("received %ld bytes, %ld errors, %lld us\n", total, errors,
           (long long)((t1 - t0) / 1000));
    close(fd);
    close(ls);
    return errors == 0 ? 0 : 1;
}

static int send_stream(const char *host, const char *port, long nbytes) {
    struct addrinfo hints = {0}, *res;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, port, &hints, &res) != 0) {
        fprintf(stderr, "getaddrinfo failed\n");
        return 1;
    }
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
        perror("connect");
        return 1;
    }
    freeaddrinfo(res);
    int64_t t0 = now_ns();
    char chunk[16384];
    long sent = 0;
    while (sent < nbytes) {
        long n = nbytes - sent < (long)sizeof(chunk) ? nbytes - sent
                                                     : (long)sizeof(chunk);
        for (long i = 0; i < n; i++)
            chunk[i] = (char)((sent + i) % 251);
        ssize_t w = write(fd, chunk, n);
        if (w < 0) {
            perror("write");
            return 1;
        }
        sent += w;
    }
    shutdown(fd, SHUT_WR);
    char b;
    while (read(fd, &b, 1) > 0) /* wait for the server's close */
        ;
    int64_t t1 = now_ns();
    printf("streamed %ld bytes, %lld us\n", sent, (long long)((t1 - t0) / 1000));
    close(fd);
    return 0;
}

int main(int argc, char **argv) {
    if (argc >= 3 && strcmp(argv[1], "serve") == 0)
        return serve(atoi(argv[2]));
    if (argc >= 5 && strcmp(argv[1], "send") == 0)
        return send_stream(argv[2], argv[3], atol(argv[4]));
    return 2;
}
