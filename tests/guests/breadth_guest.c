/* Guest test program: descriptor/identity syscall breadth under the shim.
 * dup2/dup3, readv/writev, sendmsg/recvmsg, fstat, lseek, identity calls,
 * sysinfo, sched_yield, clock_nanosleep. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/sysinfo.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s (errno=%d)\n", name, errno);                       \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

int main(void) {
    /* vectored IO over a pipe */
    int pfd[2];
    CHECK(pipe(pfd) == 0, "pipe");
    struct iovec wv[3] = {{"abc", 3}, {"", 0}, {"defgh", 5}};
    CHECK(writev(pfd[1], wv, 3) == 8, "writev");
    char b1[4] = {0}, b2[16] = {0};
    struct iovec rv[2] = {{b1, 3}, {b2, 8}};
    ssize_t r = readv(pfd[0], rv, 2);
    CHECK(r >= 3, "readv"); /* short reads are valid */
    CHECK(memcmp(b1, "abc", 3) == 0, "readv-content");

    /* dup2 onto a specific virtual slot */
    int d = dup2(pfd[0], 1500);
    CHECK(d == 1500, "dup2");
    CHECK(dup3(pfd[0], 1500, O_CLOEXEC) == 1500, "dup3-replace");
    CHECK(dup3(pfd[0], pfd[0], 0) == -1 && errno == EINVAL, "dup3-same");
    /* remaining writev bytes readable through the dup'd fd */
    ssize_t rest = read(1500, b2, sizeof(b2));
    CHECK(rest == 8 - r + 3 || rest > 0, "dup2-read");
    close(1500);
    close(pfd[0]);
    close(pfd[1]);

    /* sendmsg/recvmsg over a unix dgram socketpair */
    int sv[2];
    CHECK(socketpair(AF_UNIX, SOCK_DGRAM, 0, sv) == 0, "socketpair");
    struct iovec mv[2] = {{"ping", 4}, {"-pong", 5}};
    struct msghdr mh;
    memset(&mh, 0, sizeof(mh));
    mh.msg_iov = mv;
    mh.msg_iovlen = 2;
    CHECK(sendmsg(sv[0], &mh, 0) == 9, "sendmsg");
    char rb[32] = {0};
    struct iovec rmv = {rb, sizeof(rb)};
    struct msghdr rmh;
    memset(&rmh, 0, sizeof(rmh));
    rmh.msg_iov = &rmv;
    rmh.msg_iovlen = 1;
    CHECK(recvmsg(sv[1], &rmh, 0) == 9 && memcmp(rb, "ping-pong", 9) == 0,
          "recvmsg");

    /* MSG_PEEK: observe without consuming, then really consume */
    CHECK(sendmsg(sv[0], &mh, 0) == 9, "peek-refill");
    char pk[32] = {0};
    CHECK(recv(sv[1], pk, sizeof(pk), MSG_PEEK) == 9 &&
              memcmp(pk, "ping-pong", 9) == 0,
          "msg-peek");
    memset(pk, 0, sizeof(pk));
    CHECK(recv(sv[1], pk, sizeof(pk), 0) == 9 && memcmp(pk, "ping-pong", 9) == 0,
          "peek-then-recv");
    CHECK(recv(sv[1], pk, sizeof(pk), MSG_DONTWAIT) == -1 && errno == EAGAIN,
          "peek-consumed");

    /* fstat on a socket reports S_IFSOCK; lseek is ESPIPE */
    struct stat st;
    CHECK(fstat(sv[0], &st) == 0 && S_ISSOCK(st.st_mode), "fstat-sock");
    CHECK(lseek(sv[0], 0, SEEK_SET) == -1 && errno == ESPIPE, "lseek-espipe");
    close(sv[0]);
    close(sv[1]);

    /* identity + sysinfo determinism */
    printf("pid=%d ppid=%d uid=%d gid=%d\n", getpid(), getppid(), getuid(),
           getgid());
    struct sysinfo si;
    CHECK(sysinfo(&si) == 0 && si.totalram > 0, "sysinfo");
    printf("uptime=%ld\n", si.uptime);
    CHECK(sched_yield() == 0, "sched_yield");

    /* clock_nanosleep relative + absolute on simulated time */
    struct timespec ts = {0, 20000000};
    CHECK(clock_nanosleep(CLOCK_MONOTONIC, 0, &ts, NULL) == 0,
          "clock_nanosleep-rel");
    struct timespec now;
    clock_gettime(CLOCK_REALTIME, &now);
    struct timespec abs_t = {now.tv_sec, now.tv_nsec};
    abs_t.tv_sec += 1;
    long long t0 = (long long)now.tv_sec * 1000000000LL + now.tv_nsec;
    CHECK(clock_nanosleep(CLOCK_REALTIME, TIMER_ABSTIME, &abs_t, NULL) == 0,
          "clock_nanosleep-abs");
    clock_gettime(CLOCK_REALTIME, &now);
    long long waited = (long long)now.tv_sec * 1000000000LL + now.tv_nsec - t0;
    CHECK(waited >= 900000000LL && waited <= 1500000000LL,
          "clock_nanosleep-abs-timing");

    printf("breadth all ok\n");
    return 0;
}
