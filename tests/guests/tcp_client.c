/* TCP client guest: resolves the server by hostname (getaddrinfo -> the
 * simulated DNS), connects, sends `nbytes` of patterned data in chunks,
 * reads the echo back, verifies it, and prints the elapsed simulated time.
 * Usage: tcp_client <server-hostname> <port> <nbytes> */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char **argv) {
    if (argc < 4)
        return 2;
    const char *host = argv[1];
    const char *port = argv[2];
    long nbytes = atol(argv[3]);

    struct addrinfo hints = {0}, *res;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    int gai = getaddrinfo(host, port, &hints, &res);
    if (gai != 0) {
        fprintf(stderr, "getaddrinfo failed: %d\n", gai);
        return 1;
    }

    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
        perror("socket");
        return 1;
    }
    int64_t t0 = now_ns();
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
        perror("connect");
        return 1;
    }
    int64_t t_conn = now_ns();
    printf("connected in %lld us\n", (long long)((t_conn - t0) / 1000));
    freeaddrinfo(res);

    char chunk[4096];
    long sent = 0, rcvd = 0, errors = 0;
    long recv_expect = 0;
    char rbuf[8192];
    while (sent < nbytes) {
        long n = nbytes - sent < (long)sizeof(chunk) ? nbytes - sent
                                                     : (long)sizeof(chunk);
        for (long i = 0; i < n; i++)
            chunk[i] = (char)((sent + i) % 251);
        long off = 0;
        while (off < n) {
            ssize_t w = write(fd, chunk + off, n - off);
            if (w < 0) {
                perror("write");
                return 1;
            }
            off += w;
            sent += w;
        }
        /* drain whatever echo is available without blocking hard */
        while (rcvd < sent) {
            ssize_t r = recv(fd, rbuf, sizeof(rbuf),
                             rcvd + (long)sizeof(rbuf) < sent ? 0 : MSG_DONTWAIT);
            if (r < 0)
                break; /* EAGAIN */
            if (r == 0)
                break;
            for (ssize_t i = 0; i < r; i++)
                if (rbuf[i] != (char)((recv_expect + i) % 251))
                    errors++;
            recv_expect += r;
            rcvd += r;
        }
    }
    shutdown(fd, SHUT_WR);
    while (rcvd < nbytes) {
        ssize_t r = read(fd, rbuf, sizeof(rbuf));
        if (r < 0) {
            perror("read");
            return 1;
        }
        if (r == 0)
            break;
        for (ssize_t i = 0; i < r; i++)
            if (rbuf[i] != (char)((recv_expect + i) % 251))
                errors++;
        recv_expect += r;
        rcvd += r;
    }
    int64_t t1 = now_ns();
    close(fd);
    printf("echoed %ld/%ld bytes, %ld errors, %lld us\n", rcvd, nbytes, errors,
           (long long)((t1 - t0) / 1000));
    return (rcvd == nbytes && errors == 0) ? 0 : 1;
}
