/* rdtsc/rdtscp guest: hardware cycle counters must serve *simulated*
 * time (1 GHz nominal: cycles == sim ns), so a timed sleep measured with
 * rdtsc sees the simulated duration, deterministically. */
#include <stdint.h>
#include <stdio.h>
#include <time.h>

static inline uint64_t rdtsc(void) {
    uint32_t lo, hi;
    __asm__ volatile("rdtsc" : "=a"(lo), "=d"(hi));
    return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtscp(uint32_t *aux) {
    uint32_t lo, hi, cx;
    __asm__ volatile("rdtscp" : "=a"(lo), "=d"(hi), "=c"(cx));
    *aux = cx;
    return ((uint64_t)hi << 32) | lo;
}

int main(void) {
    uint64_t t0 = rdtsc();
    struct timespec d = {0, 25 * 1000000}; /* 25 ms sim */
    nanosleep(&d, NULL);
    uint32_t aux = 77;
    uint64_t t1 = rdtscp(&aux);
    printf("tsc_delta_ms=%llu aux=%u\n",
           (unsigned long long)((t1 - t0) / 1000000), aux);

    /* back-to-back reads are monotone non-decreasing */
    uint64_t a = rdtsc(), b = rdtsc();
    printf("monotone=%d\n", b >= a);
    return 0;
}
