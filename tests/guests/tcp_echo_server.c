/* Epoll-based TCP echo server guest: accepts `nconns` connections, echoes
 * every byte until peer EOF, then exits. Exercises listen/accept/epoll/
 * nonblocking reads against the simulated TCP stack.
 * Usage: tcp_echo_server <port> <nconns> */
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc < 3)
        return 2;
    int port = atoi(argv[1]);
    int want = atoi(argv[2]);

    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    if (lfd < 0) {
        perror("socket");
        return 1;
    }
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in sa = {0};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
    sa.sin_port = htons(port);
    if (bind(lfd, (struct sockaddr *)&sa, sizeof(sa)) != 0) {
        perror("bind");
        return 1;
    }
    if (listen(lfd, 16) != 0) {
        perror("listen");
        return 1;
    }

    int ep = epoll_create1(0);
    struct epoll_event ev = {0};
    ev.events = EPOLLIN;
    ev.data.fd = lfd;
    epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);

    int done = 0;
    long total = 0;
    char buf[8192];
    while (done < want) {
        struct epoll_event evs[16];
        int n = epoll_wait(ep, evs, 16, 30000);
        if (n < 0) {
            perror("epoll_wait");
            return 1;
        }
        if (n == 0) {
            fprintf(stderr, "timeout\n");
            return 1;
        }
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == lfd) {
                struct sockaddr_in peer;
                socklen_t pl = sizeof(peer);
                int cfd = accept(lfd, (struct sockaddr *)&peer, &pl);
                if (cfd < 0) {
                    perror("accept");
                    return 1;
                }
                printf("accept from %s:%d\n", inet_ntoa(peer.sin_addr),
                       ntohs(peer.sin_port));
                struct epoll_event cev = {0};
                cev.events = EPOLLIN;
                cev.data.fd = cfd;
                epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &cev);
            } else {
                ssize_t r = read(fd, buf, sizeof(buf));
                if (r < 0) {
                    perror("read");
                    return 1;
                }
                if (r == 0) { /* peer EOF: close our side too */
                    epoll_ctl(ep, EPOLL_CTL_DEL, fd, NULL);
                    close(fd);
                    done++;
                    continue;
                }
                total += r;
                ssize_t off = 0;
                while (off < r) {
                    ssize_t w = write(fd, buf + off, r - off);
                    if (w < 0) {
                        perror("write");
                        return 1;
                    }
                    off += w;
                }
            }
        }
    }
    printf("served %d conns, %ld bytes\n", done, total);
    return 0;
}
