/* Exercises the non-socket descriptor families end to end inside the
 * simulation: pipes, eventfd, timerfd, poll, fcntl/O_NONBLOCK, dup,
 * getrandom, uname, gethostname. Prints PASS/FAIL lines per check. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/random.h>
#include <sys/utsname.h>
#include <time.h>
#include <unistd.h>

extern int eventfd(unsigned int initval, int flags);
extern int timerfd_create(int clockid, int flags);
extern int timerfd_settime(int fd, int flags, const void *nv, void *ov);

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

#define CHECK(name, cond)                                                      \
    printf("%s %s\n", (cond) ? "PASS" : "FAIL", name)

int main(void) {
    /* pipes */
    int p[2];
    CHECK("pipe", pipe(p) == 0);
    const char *msg = "through the pipe";
    CHECK("pipe_write", write(p[1], msg, strlen(msg)) == (ssize_t)strlen(msg));
    char buf[64] = {0};
    CHECK("pipe_read", read(p[0], buf, sizeof(buf)) == (ssize_t)strlen(msg));
    CHECK("pipe_data", strcmp(buf, msg) == 0);

    /* nonblocking read on empty pipe */
    CHECK("fcntl_setfl", fcntl(p[0], F_SETFL, O_NONBLOCK) == 0);
    CHECK("fcntl_getfl", (fcntl(p[0], F_GETFL, 0) & O_NONBLOCK) != 0);
    errno = 0;
    CHECK("pipe_eagain", read(p[0], buf, sizeof(buf)) == -1 && errno == EAGAIN);
    fcntl(p[0], F_SETFL, 0);

    /* dup shares the pipe */
    int pdup = dup(p[1]);
    CHECK("dup", pdup >= 3); /* unified fd space: lowest-free real numbers */
    CHECK("dup_write", write(pdup, "x", 1) == 1);
    CHECK("dup_read", read(p[0], buf, 1) == 1 && buf[0] == 'x');

    /* EOF after closing both write ends */
    close(p[1]);
    close(pdup);
    CHECK("pipe_eof", read(p[0], buf, sizeof(buf)) == 0);
    close(p[0]);

    /* eventfd */
    int efd = eventfd(3, 0);
    CHECK("eventfd", efd >= 3);
    uint64_t v = 0;
    CHECK("eventfd_read", read(efd, &v, 8) == 8 && v == 3);
    v = 7;
    CHECK("eventfd_write", write(efd, &v, 8) == 8);
    CHECK("eventfd_read2", read(efd, &v, 8) == 8 && v == 7);

    /* timerfd: 50ms one-shot; blocking read must advance sim time ~50ms */
    int tfd = timerfd_create(CLOCK_MONOTONIC, 0);
    CHECK("timerfd_create", tfd >= 3);
    struct timespec its[2] = {{0, 0}, {0, 50 * 1000000}};
    CHECK("timerfd_settime", timerfd_settime(tfd, 0, its, NULL) == 0);
    int64_t t0 = now_ns();
    uint64_t expir = 0;
    CHECK("timerfd_read", read(tfd, &expir, 8) == 8 && expir == 1);
    int64_t dt = now_ns() - t0;
    CHECK("timerfd_50ms", dt >= 49 * 1000000LL && dt < 200 * 1000000LL);

    /* periodic timer: 10ms interval, read twice -> >=1 expiration each */
    struct timespec its2[2] = {{0, 10 * 1000000}, {0, 10 * 1000000}};
    timerfd_settime(tfd, 0, its2, NULL);
    read(tfd, &expir, 8);
    CHECK("timerfd_periodic", expir >= 1);
    close(tfd);

    /* poll: timeout-only poll advances sim time */
    t0 = now_ns();
    int pr = poll(NULL, 0, 20); /* no vfds: native path, wall time — skip */
    (void)pr;

    /* poll on an armed eventfd */
    struct pollfd pfd = {.fd = efd, .events = POLLIN};
    v = 1;
    write(efd, &v, 8);
    CHECK("poll_ready", poll(&pfd, 1, 1000) == 1 && (pfd.revents & POLLIN));
    read(efd, &v, 8);
    t0 = now_ns();
    CHECK("poll_timeout", poll(&pfd, 1, 30) == 0);
    dt = now_ns() - t0;
    CHECK("poll_timeout_30ms", dt >= 29 * 1000000LL && dt < 200 * 1000000LL);
    close(efd);

    /* deterministic getrandom */
    unsigned char r1[16], r2[16];
    CHECK("getrandom", getrandom(r1, 16, 0) == 16);
    CHECK("getrandom2", getrandom(r2, 16, 0) == 16);
    CHECK("getrandom_distinct", memcmp(r1, r2, 16) != 0);
    printf("rand ");
    for (int i = 0; i < 16; i++)
        printf("%02x", r1[i]);
    printf("\n");

    /* identity */
    struct utsname un;
    CHECK("uname", uname(&un) == 0 && strcmp(un.sysname, "Linux") == 0);
    char hn[256];
    CHECK("gethostname", gethostname(hn, sizeof(hn)) == 0);
    printf("host %s / %s\n", hn, un.nodename);
    return 0;
}
