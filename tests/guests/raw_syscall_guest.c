/* Guest test program: raw syscall instructions that bypass the libc
 * symbol layer entirely — the seccomp SIGSYS tier must route them into
 * the simulation (reference: shim_seccomp.c + the static-bin/Go-runtime
 * motivation). Also proves vdso time reads are trapped (patch_vdso). */
#include <errno.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s (errno=%d)\n", name, errno);                       \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

int main(void) {
    /* raw clock_gettime: glibc routes this through the vdso, never a
     * trappable PLT call — only the vdso patch + seccomp catch it.
     * Simulated time starts at 2000-01-01 (946684800). */
    struct timespec ts;
    CHECK(syscall(SYS_clock_gettime, CLOCK_REALTIME, &ts) == 0, "raw-clock");
    CHECK(ts.tv_sec >= 946684800 && ts.tv_sec < 946684800 + 3600,
          "raw-clock-epoch");

    /* raw getpid must see the virtual pid */
    long pid = syscall(SYS_getpid);
    CHECK(pid >= 1000, "raw-getpid");

    /* raw nanosleep advances only simulated time */
    struct timespec t0, t1, d = {0, 250000000};
    syscall(SYS_clock_gettime, CLOCK_REALTIME, &t0);
    CHECK(syscall(SYS_nanosleep, &d, NULL) == 0, "raw-nanosleep");
    syscall(SYS_clock_gettime, CLOCK_REALTIME, &t1);
    long long waited = (t1.tv_sec - t0.tv_sec) * 1000000000LL +
                       (t1.tv_nsec - t0.tv_nsec);
    CHECK(waited >= 250000000LL && waited <= 400000000LL, "raw-sleep-simtime");

    /* raw UDP socket loop back to ourselves through the simulated stack */
    long fd = syscall(SYS_socket, AF_INET, SOCK_DGRAM, 0);
    CHECK(fd >= 3, "raw-socket-vfd"); /* lowest-free real number, routed */
    struct sockaddr_in a;
    memset(&a, 0, sizeof(a));
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = htons(9000);
    CHECK(syscall(SYS_bind, fd, &a, sizeof(a)) == 0, "raw-bind");
    struct sockaddr_in dst = a;
    dst.sin_addr.s_addr = htonl(0x7F000001); /* 127.0.0.1 -> self */
    CHECK(syscall(SYS_sendto, fd, "rawping", 7, 0, &dst, sizeof(dst)) == 7,
          "raw-sendto");
    char buf[64];
    long r = syscall(SYS_recvfrom, fd, buf, sizeof(buf), 0, NULL, NULL);
    CHECK(r == 7 && memcmp(buf, "rawping", 7) == 0, "raw-recvfrom");
    CHECK(syscall(SYS_close, fd) == 0, "raw-close");

    /* vdso path through libc (clock_gettime via vdso, no syscall insn in
     * the unpatched case): must still read simulated time */
    struct timespec vd;
    clock_gettime(CLOCK_MONOTONIC, &vd);
    printf("vdso-path sec=%lld\n", (long long)vd.tv_sec);

    printf("raw all ok\n");
    return 0;
}
