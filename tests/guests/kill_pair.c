/* Guest test program: cross-process signals at simulated time.
 * Usage:
 *   kill_pair wait            — install SIGUSR1 handler, pause until hit
 *   kill_pair send <vpid>     — sleep 100ms, kill(vpid, SIGUSR1)
 *   kill_pair victim          — pause forever (no handlers; killed by test)
 */
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

static volatile int hits = 0;
static void on_usr1(int s) { (void)s; hits++; }

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char **argv) {
    if (argc < 2)
        return 2;
    if (strcmp(argv[1], "wait") == 0) {
        struct sigaction sa;
        memset(&sa, 0, sizeof(sa));
        sa.sa_handler = on_usr1;
        sigaction(SIGUSR1, &sa, NULL);
        while (hits == 0) {
            if (pause() != -1 || errno != EINTR)
                return 3;
        }
        printf("signaled at %lld\n", now_ns());
        return 0;
    }
    if (strcmp(argv[1], "send") == 0) {
        struct timespec d = {0, 100000000};
        nanosleep(&d, NULL);
        if (kill((pid_t)atoi(argv[2]), SIGUSR1) != 0)
            return 4;
        printf("sent at %lld\n", now_ns());
        return 0;
    }
    if (strcmp(argv[1], "victim") == 0) {
        for (;;)
            pause();
    }
    return 2;
}
