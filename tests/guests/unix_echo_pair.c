/* Guest test program: unix-domain stream echo across two processes on the
 * same simulated host. Usage:
 *   unix_echo_pair server <name> <n>
 *   unix_echo_pair client <name> <n> <gap_ms>
 * Abstract-namespace address <name>. The server accepts one connection and
 * echoes n messages; the client sends n messages, checks the echoes, then
 * shuts down. Exercises blocking accept/recv across process boundaries. */
#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

static void abs_addr(struct sockaddr_un *un, socklen_t *len, const char *name) {
    memset(un, 0, sizeof(*un));
    un->sun_family = AF_UNIX;
    un->sun_path[0] = '\0';
    strcpy(un->sun_path + 1, name);
    *len = (socklen_t)(offsetof(struct sockaddr_un, sun_path) + 1 + strlen(name));
}

int main(int argc, char **argv) {
    if (argc < 4)
        return 2;
    int n = atoi(argv[3]);
    struct sockaddr_un a;
    socklen_t alen;
    abs_addr(&a, &alen, argv[2]);
    char buf[512];

    if (strcmp(argv[1], "server") == 0) {
        int srv = socket(AF_UNIX, SOCK_STREAM, 0);
        if (srv < 0 || bind(srv, (struct sockaddr *)&a, alen) != 0 ||
            listen(srv, 2) != 0)
            return 3;
        int c = accept(srv, NULL, NULL); /* blocks until the client starts */
        if (c < 0)
            return 4;
        for (int i = 0; i < n; i++) {
            ssize_t r = recv(c, buf, sizeof(buf), 0);
            if (r <= 0)
                return 5;
            if (send(c, buf, (size_t)r, 0) != r)
                return 6;
        }
        if (recv(c, buf, sizeof(buf), 0) != 0) /* client shutdown -> EOF */
            return 7;
        printf("server echoed %d\n", n);
        close(c);
        close(srv);
        return 0;
    }

    int gap_ms = argc > 4 ? atoi(argv[4]) : 0;
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0 || connect(fd, (struct sockaddr *)&a, alen) != 0)
        return 8;
    for (int i = 0; i < n; i++) {
        int len = snprintf(buf, sizeof(buf), "msg-%d", i);
        if (send(fd, buf, (size_t)len, 0) != len)
            return 9;
        char echo[512];
        ssize_t r = recv(fd, echo, sizeof(echo), 0);
        if (r != len || memcmp(buf, echo, (size_t)len) != 0)
            return 10;
        if (gap_ms > 0) {
            struct timespec ts = {gap_ms / 1000, (long)(gap_ms % 1000) * 1000000L};
            nanosleep(&ts, NULL);
        }
    }
    shutdown(fd, SHUT_WR);
    printf("client done %d\n", n);
    close(fd);
    return 0;
}
