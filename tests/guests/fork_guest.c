/* Guest test program: fork/waitpid under the shim. The parent forks two
 * children; each child talks UDP to itself through the simulated stack,
 * sleeps on simulated time, and exits with a distinct code; the parent
 * waitpids both and checks pids, statuses, and that a shared pipe written
 * by children reaches the parent (fd inheritance across fork). */
#include <errno.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s (errno=%d)\n", name, errno);                       \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

int main(void) {
    int pfd[2];
    CHECK(pipe(pfd) == 0, "pipe");
    pid_t kids[2];
    for (int i = 0; i < 2; i++) {
        pid_t p = fork();
        CHECK(p >= 0, "fork");
        if (p == 0) {
            /* child: distinct vpid, sim-time sleep, UDP self-ping */
            struct timespec d = {0, (i + 1) * 50000000L};
            nanosleep(&d, NULL);
            char msg[64];
            int n = snprintf(msg, sizeof(msg), "child-%d pid=%d", i, getpid());
            write(pfd[1], msg, (size_t)n);
            _Exit(0); /* skip parent's atexit/stdio (standard practice) */
        }
        kids[i] = p;
        printf("forked %d -> vpid %d\n", i, p);
    }
    CHECK(kids[0] != kids[1] && kids[0] >= 1000, "vpids-distinct");

    int st = -1;
    pid_t r = waitpid(kids[0], &st, 0);
    CHECK(r == kids[0], "waitpid-first");
    CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0, "status-first");
    r = wait(&st);
    CHECK(r == kids[1], "wait-second");
    CHECK(waitpid(kids[0], &st, 0) == -1 && errno == ECHILD, "echild");

    char buf[256] = {0};
    ssize_t got = read(pfd[0], buf, sizeof(buf) - 1);
    CHECK(got > 0 && strstr(buf, "child-0") != NULL, "pipe-inherited");

    printf("fork all ok\n");
    return 0;
}
