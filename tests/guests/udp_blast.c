/* Guest test program: UDP blast for bandwidth-shaping tests.
 * sender: udp_blast send <ip> <port> <count> <size>
 * sink:   udp_blast sink <port> <count>   (prints first/last arrival) */
#include <arpa/inet.h>
#include <poll.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char **argv) {
    if (argc < 3)
        return 2;
    if (strcmp(argv[1], "send") == 0) {
        if (argc < 6)
            return 2;
        int port = atoi(argv[3]), count = atoi(argv[4]), size = atoi(argv[5]);
        int fd = socket(AF_INET, SOCK_DGRAM, 0);
        struct sockaddr_in dst;
        memset(&dst, 0, sizeof(dst));
        dst.sin_family = AF_INET;
        dst.sin_port = htons((unsigned short)port);
        inet_pton(AF_INET, argv[2], &dst.sin_addr);
        char *buf = calloc(1, (size_t)size);
        long long t0 = now_ns();
        for (int i = 0; i < count; i++)
            sendto(fd, buf, (size_t)size, 0, (struct sockaddr *)&dst, sizeof(dst));
        printf("sent %d x %dB in %lld ns\n", count, size, now_ns() - t0);
        close(fd);
        return 0;
    }
    if (strcmp(argv[1], "sink") == 0) {
        if (argc < 4)
            return 2;
        int port = atoi(argv[2]), count = atoi(argv[3]);
        int fd = socket(AF_INET, SOCK_DGRAM, 0);
        struct sockaddr_in a;
        memset(&a, 0, sizeof(a));
        a.sin_family = AF_INET;
        a.sin_addr.s_addr = htonl(INADDR_ANY);
        a.sin_port = htons((unsigned short)port);
        if (bind(fd, (struct sockaddr *)&a, sizeof(a)) != 0)
            return 3;
        char buf[65536];
        long long first = 0, last = 0;
        int got = 0;
        while (got < count) {
            struct pollfd p = {.fd = fd, .events = POLLIN};
            int pr = poll(&p, 1, 2000); /* drops may leave us short */
            if (pr <= 0)
                break;
            ssize_t r = recv(fd, buf, sizeof(buf), 0);
            if (r <= 0)
                break;
            got++;
            last = now_ns();
            if (!first)
                first = last;
        }
        printf("got %d first %lld last %lld span %lld ns\n", got, first, last,
               last - first);
        close(fd);
        return 0;
    }
    return 2;
}
