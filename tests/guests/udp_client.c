/* Guest test program: UDP client. Usage: udp_client <ip> <port> <n> <gap_ms>
 * Sends n datagrams, waits for each echo, prints simulated-clock RTTs. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char **argv) {
    if (argc < 5)
        return 2;
    int port = atoi(argv[2]);
    int n = atoi(argv[3]);
    int gap_ms = atoi(argv[4]);
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0)
        return 3;
    struct sockaddr_in dst;
    memset(&dst, 0, sizeof(dst));
    dst.sin_family = AF_INET;
    dst.sin_port = htons((unsigned short)port);
    if (inet_pton(AF_INET, argv[1], &dst.sin_addr) != 1)
        return 4;
    char msg[256], buf[4096];
    for (int i = 0; i < n; i++) {
        int len = snprintf(msg, sizeof(msg), "ping-%d", i);
        long long t0 = now_ns();
        sendto(fd, msg, (size_t)len, 0, (struct sockaddr *)&dst, sizeof(dst));
        ssize_t r = recvfrom(fd, buf, sizeof(buf) - 1, 0, NULL, NULL);
        long long t1 = now_ns();
        if (r < 0)
            return 5;
        buf[r] = 0;
        printf("rtt %d %lld ns reply=%s\n", i, t1 - t0, buf);
        if (gap_ms > 0) {
            struct timespec ts = {gap_ms / 1000,
                                  (long)(gap_ms % 1000) * 1000000L};
            nanosleep(&ts, NULL);
        }
    }
    close(fd);
    printf("client done t=%lld\n", now_ns());
    return 0;
}
