/* Syscall-breadth guest: the nginx-grade file/metadata surface the
 * round-3 verdict listed (reference checklist:
 * src/main/host/syscall_handler.c:301-463): getdents64, statx,
 * newfstatat, access/faccessat, readlink(at), getcwd/chdir,
 * sched_getaffinity, sysinfo, prlimit64, times/getrusage, and the
 * deterministic /proc views. Prints a transcript that must be
 * byte-identical across runs and contain only simulated values. */
#define _GNU_SOURCE
#include <dirent.h>
#include <fcntl.h>
#include <sched.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/sysinfo.h>
#include <sys/syscall.h>
#include <sys/times.h>
#include <unistd.h>

static int cmpstr(const void *a, const void *b) {
    return strcmp(*(const char *const *)a, *(const char *const *)b);
}

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0);

    /* getcwd / mkdir / chdir */
    char cwd0[512], cwd1[512];
    if (!getcwd(cwd0, sizeof(cwd0)))
        return 1;
    mkdir("subdir", 0755);
    if (chdir("subdir") != 0)
        return 2;
    getcwd(cwd1, sizeof(cwd1));
    printf("chdir ok: %d\n", strlen(cwd1) > strlen(cwd0));
    chdir("..");

    /* files + getdents64 via readdir */
    for (int i = 0; i < 3; i++) {
        char name[32];
        snprintf(name, sizeof(name), "f%d.txt", i);
        FILE *f = fopen(name, "w");
        fprintf(f, "hello %d\n", i);
        fclose(f);
    }
    DIR *d = opendir(".");
    if (!d)
        return 3;
    char *names[64];
    int n = 0;
    struct dirent *de;
    while ((de = readdir(d)) && n < 64)
        if (de->d_name[0] != '.')
            names[n++] = strdup(de->d_name);
    closedir(d);
    qsort(names, n, sizeof(char *), cmpstr);
    printf("dirents:");
    for (int i = 0; i < n; i++)
        printf(" %s", names[i]);
    printf("\n");

    /* stat family */
    struct stat st;
    if (stat("f1.txt", &st) != 0)
        return 4;
    printf("stat size %lld mode %o\n", (long long)st.st_size,
           st.st_mode & 0777);
    struct statx sx;
    if (syscall(SYS_statx, AT_FDCWD, "f1.txt", 0, 0x7ff, &sx) == 0)
        printf("statx size %llu\n", (unsigned long long)sx.stx_size);
    else
        printf("statx unsupported\n");

    /* access / faccessat */
    printf("access rw %d missing %d\n", access("f1.txt", R_OK | W_OK),
           access("nope.txt", F_OK));
    printf("faccessat %d\n", faccessat(AT_FDCWD, "f2.txt", R_OK, 0));

    /* readlink */
    symlink("f0.txt", "link0");
    char lbuf[64];
    ssize_t ln = readlink("link0", lbuf, sizeof(lbuf) - 1);
    lbuf[ln > 0 ? ln : 0] = '\0';
    printf("readlink %s\n", lbuf);

    /* sched_getaffinity: exactly one simulated cpu */
    cpu_set_t cs;
    CPU_ZERO(&cs);
    sched_getaffinity(0, sizeof(cs), &cs);
    printf("cpus %d\n", CPU_COUNT(&cs));
    printf("nprocs %d\n", get_nprocs());

    /* sysinfo: fixed simulated memory, sim uptime */
    struct sysinfo si;
    sysinfo(&si);
    printf("sysinfo ram %lu procs %d uptime<10 %d\n",
           (unsigned long)(si.totalram >> 30), si.procs, si.uptime < 10);

    /* prlimit64 roundtrip */
    struct rlimit rl;
    getrlimit(RLIMIT_NOFILE, &rl);
    printf("nofile %llu\n", (unsigned long long)rl.rlim_cur);
    struct rlimit nrl = {512, rl.rlim_max};
    printf("setrlim %d\n", setrlimit(RLIMIT_NOFILE, &nrl));
    getrlimit(RLIMIT_NOFILE, &rl);
    printf("nofile2 %llu\n", (unsigned long long)rl.rlim_cur);

    /* deterministic /proc views */
    char buf[4096];
    FILE *f = fopen("/proc/self/status", "r");
    if (!f)
        return 5;
    while (fgets(buf, sizeof(buf), f))
        if (strncmp(buf, "Pid:", 4) == 0 || strncmp(buf, "Threads:", 8) == 0)
            printf("status %s", buf);
    fclose(f);
    f = fopen("/proc/meminfo", "r");
    if (f && fgets(buf, sizeof(buf), f))
        printf("meminfo %s", buf);
    if (f)
        fclose(f);
    f = fopen("/proc/uptime", "r");
    if (f && fgets(buf, sizeof(buf), f))
        printf("uptime-digits %d\n", (int)(strchr(buf, '.') - buf));
    if (f)
        fclose(f);
    f = fopen("/proc/loadavg", "r");
    if (f && fgets(buf, sizeof(buf), f))
        printf("loadavg %s", buf);
    if (f)
        fclose(f);
    f = fopen("/proc/sys/net/core/somaxconn", "r");
    if (f && fgets(buf, sizeof(buf), f))
        printf("somaxconn %s", buf);
    if (f)
        fclose(f);

    /* pid visible to the guest is the virtual pid */
    printf("pid %d\n", (int)getpid());

    /* times/getrusage derived from sim clock */
    struct tms tm;
    long t = (long)times(&tm);
    printf("times<1000 %d\n", t < 1000);
    struct rusage ru;
    getrusage(RUSAGE_SELF, &ru);
    printf("maxrss %ld\n", ru.ru_maxrss);

    printf("breadth all ok\n");
    return 0;
}
