// Guest test program: the C++ runtime over the shim (reference:
// src/test/cpp). std::thread -> pthreads, std::mutex/condition_variable ->
// kernel-side sync, chrono/sleep_for -> simulated clocks, iostreams, and
// a TCP self-connection through the simulated stack.
#include <arpa/inet.h>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <mutex>
#include <netinet/in.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            std::cout << "FAIL " << name << std::endl;                         \
            return 1;                                                          \
        }                                                                      \
        std::cout << "ok " << name << std::endl;                               \
    } while (0)

int main() {
    using clk = std::chrono::system_clock;

    // chrono reads simulated time (epoch 2000-01-01) — sim only; natively
    // the epoch is the real date
    auto t0 = clk::now();
    if (getenv("SHADOW_SHM")) {
        auto secs = std::chrono::duration_cast<std::chrono::seconds>(
                        t0.time_since_epoch())
                        .count();
        CHECK(secs >= 946684800 && secs < 946684800 + 3600, "chrono-epoch");
    }

    // sleep_for advances only simulated time (the tight upper bound is
    // deterministic only under the shim; natively the OS may overshoot)
    std::this_thread::sleep_for(std::chrono::milliseconds(120));
    auto waited = std::chrono::duration_cast<std::chrono::milliseconds>(
                      clk::now() - t0)
                      .count();
    bool in_sim = getenv("SHADOW_SHM") != nullptr;
    CHECK(waited >= 120 && (!in_sim || waited <= 200), "sleep_for");

    // std::thread + mutex + condition_variable
    std::mutex mu;
    std::condition_variable cv;
    int produced = 0;
    long sum = 0;
    std::thread producer([&] {
        for (int i = 1; i <= 5; i++) {
            std::this_thread::sleep_for(std::chrono::milliseconds(10));
            std::lock_guard<std::mutex> g(mu);
            produced = i;
            cv.notify_one();
        }
    });
    std::thread consumer([&] {
        int seen = 0;
        std::unique_lock<std::mutex> lk(mu);
        while (seen < 5) {
            cv.wait(lk, [&] { return produced > seen; });
            seen = produced;
            sum += seen;
        }
    });
    producer.join();
    consumer.join();
    CHECK(sum >= 15, "thread-condvar");

    // TCP through the simulated loopback
    int srv = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = htons(8080);
    CHECK(bind(srv, (sockaddr *)&a, sizeof(a)) == 0 && listen(srv, 4) == 0,
          "tcp-listen");
    std::string got;
    std::thread server([&] {
        int c = accept(srv, nullptr, nullptr);
        char buf[128];
        ssize_t r = recv(c, buf, sizeof(buf), 0);
        if (r > 0)
            got.assign(buf, (size_t)r);
        send(c, "pong", 4, 0);
        close(c);
    });
    int cli = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in dst = a;
    dst.sin_addr.s_addr = htonl(0x7F000001);
    CHECK(connect(cli, (sockaddr *)&dst, sizeof(dst)) == 0, "tcp-connect");
    send(cli, "ping", 4, 0);
    char rb[16];
    ssize_t r = recv(cli, rb, sizeof(rb), 0);
    server.join();
    CHECK(r == 4 && std::memcmp(rb, "pong", 4) == 0 && got == "ping",
          "tcp-echo");
    close(cli);
    close(srv);

    std::cout << "cpp all ok sum=" << sum << std::endl;
    return 0;
}
