/* RR-qdisc guest: two UDP sockets on one host each blast a tagged burst
 * back to back over a shaped uplink. Under fifo the whole A-burst
 * precedes the B-burst on the wire; under rr the NIC round-robins the
 * two sockets' queues. The sink prints the arrival tag order.
 *   rr_guest sink <port> <count>
 *   rr_guest send <ip> <port> <per_sock> */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc >= 4 && strcmp(argv[1], "sink") == 0) {
        int port = atoi(argv[2]), count = atoi(argv[3]);
        int fd = socket(AF_INET, SOCK_DGRAM, 0);
        struct sockaddr_in a = {0};
        a.sin_family = AF_INET;
        a.sin_port = htons((uint16_t)port);
        a.sin_addr.s_addr = htonl(INADDR_ANY);
        if (bind(fd, (struct sockaddr *)&a, sizeof(a)) != 0) {
            perror("bind");
            return 1;
        }
        char order[256] = {0};
        char buf[64];
        for (int i = 0; i < count && i < 250; i++) {
            ssize_t r = recv(fd, buf, sizeof(buf) - 1, 0);
            if (r <= 0)
                break;
            order[i] = buf[0];
        }
        printf("order=%s\n", order);
        return 0;
    }
    if (argc >= 5 && strcmp(argv[1], "send") == 0) {
        int port = atoi(argv[3]), per = atoi(argv[4]);
        struct sockaddr_in a = {0};
        a.sin_family = AF_INET;
        a.sin_port = htons((uint16_t)port);
        a.sin_addr.s_addr = inet_addr(argv[2]);
        int sa = socket(AF_INET, SOCK_DGRAM, 0);
        int sb = socket(AF_INET, SOCK_DGRAM, 0);
        char pkt[1000];
        memset(pkt, 'x', sizeof(pkt));
        for (int i = 0; i < per; i++) {
            pkt[0] = 'A';
            sendto(sa, pkt, sizeof(pkt), 0, (struct sockaddr *)&a, sizeof(a));
        }
        for (int i = 0; i < per; i++) {
            pkt[0] = 'B';
            sendto(sb, pkt, sizeof(pkt), 0, (struct sockaddr *)&a, sizeof(a));
        }
        close(sa);
        close(sb);
        return 0;
    }
    return 2;
}
