/* Guest test program: signals on simulated time within one process.
 * alarm/SIGALRM interrupting nanosleep, setitimer interval ticks via
 * pause, self-kill synchronous delivery, SIG_IGN, alarm cancellation.
 * Prints "ok <step>"; exits 0 only if all steps passed. */
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

#define CHECK(cond, name)                                                      \
    do {                                                                       \
        if (!(cond)) {                                                         \
            printf("FAIL %s\n", name);                                         \
            return 1;                                                          \
        }                                                                      \
        printf("ok %s\n", name);                                               \
    } while (0)

#include <sys/socket.h>

static volatile int alarms = 0, usr1s = 0, usr2s = 0;
static int g_sp[2];
static void on_alrm(int s) { (void)s; alarms++; }
static void on_alrm_send(int s) {
    (void)s;
    alarms++;
    send(g_sp[1], "wake", 4, 0); /* unblocks the restarted recv */
}
static void on_usr1(int s) { (void)s; usr1s++; }
static void on_usr2(int s) { (void)s; usr2s++; }

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_alrm;
    CHECK(sigaction(SIGALRM, &sa, NULL) == 0, "sigaction");

    /* alarm interrupts nanosleep with EINTR and correct remaining time */
    long long t0 = now_ns();
    alarm(1);
    struct timespec req = {5, 0}, rem = {0, 0};
    int r = nanosleep(&req, &rem);
    long long waited = now_ns() - t0;
    CHECK(r == -1 && errno == EINTR, "sleep-eintr");
    CHECK(alarms == 1, "alarm-fired");
    CHECK(waited >= 900000000LL && waited <= 1500000000LL, "alarm-at-1s");
    CHECK(rem.tv_sec >= 3 && rem.tv_sec <= 4, "sleep-remaining");

    /* interval timer ticks pause() on a 100ms cadence */
    t0 = now_ns();
    struct itimerval itv = {{0, 100000}, {0, 100000}}; /* 100ms/100ms */
    CHECK(setitimer(ITIMER_REAL, &itv, NULL) == 0, "setitimer");
    for (int i = 0; i < 3; i++)
        CHECK(pause() == -1 && errno == EINTR, "pause-tick");
    long long ticked = now_ns() - t0;
    CHECK(alarms == 4, "itimer-count");
    CHECK(ticked >= 290000000LL && ticked <= 500000000LL, "itimer-cadence");
    struct itimerval zero = {{0, 0}, {0, 0}}, old;
    CHECK(setitimer(ITIMER_REAL, &zero, &old) == 0, "setitimer-disarm");
    CHECK(old.it_interval.tv_usec == 100000, "setitimer-old-interval");

    /* self-kill: handler runs before kill() returns */
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_usr1;
    sigaction(SIGUSR1, &sa, NULL);
    CHECK(kill(getpid(), SIGUSR1) == 0, "self-kill");
    CHECK(usr1s == 1, "self-kill-sync");

    /* ignored signals are dropped */
    signal(SIGUSR2, SIG_IGN);
    CHECK(kill(getpid(), SIGUSR2) == 0, "kill-ignored");
    CHECK(usr2s == 0, "ignored-dropped");
    signal(SIGUSR2, on_usr2);
    CHECK(kill(getpid(), SIGUSR2) == 0 && usr2s == 1, "rearmed-handler");

    /* alarm(0) cancels and reports remaining seconds */
    alarm(3);
    unsigned int remaining = alarm(0);
    CHECK(remaining >= 2 && remaining <= 3, "alarm-cancel");
    struct timespec ok = {0, 50000000};
    CHECK(nanosleep(&ok, NULL) == 0 && alarms == 4, "no-stray-alarm");

    /* SA_RESTART: a blocking recv interrupted by SIGALRM restarts after
     * the handler (which itself sends the wakeup datagram) */
    CHECK(socketpair(AF_UNIX, SOCK_DGRAM, 0, g_sp) == 0, "restart-socketpair");
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_alrm_send;
    sa.sa_flags = SA_RESTART;
    CHECK(sigaction(SIGALRM, &sa, NULL) == 0, "restart-sigaction");
    alarm(1);
    t0 = now_ns();
    char b2[16];
    ssize_t rr = recv(g_sp[0], b2, sizeof(b2), 0);
    CHECK(rr == 4 && memcmp(b2, "wake", 4) == 0, "sa-restart");
    CHECK(now_ns() - t0 >= 900000000LL, "sa-restart-waited");
    CHECK(alarms == 5, "sa-restart-count");
    close(g_sp[0]);
    close(g_sp[1]);

    /* kill to a nonexistent sim pid (only meaningful under the shim,
     * where pids >= 1000 are virtual; natively 4242 might exist) */
    if (getenv("SHADOW_SHM"))
        CHECK(kill(4242, 0) == -1 && errno == ESRCH, "kill-esrch");

    printf("signals all ok\n");
    return 0;
}
