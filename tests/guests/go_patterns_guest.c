/* Go-runtime thread patterns, in C (no Go toolchain on this image; the
 * acceptance programs mirror src/test/golang/test_goroutines.go's
 * runtime-level behavior): raw clone(CLONE_THREAD) M creation with
 * CLONE_CHILD_SETTID + CLONE_CHILD_CLEARTID, ctid-futex join (Go's
 * thread exit protocol), per-thread sigaltstack (gsignal), and SIGURG
 * async-preemption IPIs delivered cross-thread by virtual tid while the
 * target spins in compute (no blocking syscalls). */
#define _GNU_SOURCE
#include <sched.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <time.h>
#include <unistd.h>

static long rsys(long nr, long a1, long a2, long a3, long a4, long a5) {
    long ret;
    register long r10 asm("r10") = a4;
    register long r8 asm("r8") = a5;
    asm volatile("syscall"
                 : "=a"(ret)
                 : "0"(nr), "D"(a1), "S"(a2), "d"(a3), "r"(r10), "r"(r8)
                 : "rcx", "r11", "memory");
    return ret;
}

#define SYS_futex_ 202
#define SYS_tgkill_ 234
#define SYS_getpid_ 39
#define FUTEX_WAIT_ 0

#define NTHREADS 2
#define NPREEMPT 3

static volatile int g_ctid[NTHREADS];     /* settid/cleartid words */
static volatile int g_settid[NTHREADS];   /* observed by the child */
static volatile int g_sigs[NTHREADS];     /* SIGURG deliveries */
static volatile int g_stop[NTHREADS];
static volatile int g_ready[NTHREADS];
static volatile long g_spun[NTHREADS];

/* raw clone without CLONE_SETTLS => no per-thread TLS (it would alias the
 * parent's, exactly like Go Ms before runtime TLS setup): identify the
 * running worker by stack range instead */
static char *g_stackbase[NTHREADS];
#define STACK_SZ (256 * 1024)

static int self_idx(void) {
    char probe;
    for (int i = 0; i < NTHREADS; i++)
        if (g_stackbase[i] && (char *)&probe >= g_stackbase[i] &&
            (char *)&probe < g_stackbase[i] + STACK_SZ)
            return i;
    return -1;
}

static void urg_handler(int sig) {
    (void)sig;
    int i = self_idx();
    if (i < 0)
        return;
    int n = ++g_sigs[i];
    if (n >= NPREEMPT)
        g_stop[i] = 1;
}

struct targ {
    int idx;
};
static struct targ g_args[NTHREADS];

static int worker(void *arg) {
    struct targ *ta = arg;
    int idx = ta->idx;

    /* per-thread gsignal-style alternate stack */
    static char altstacks[NTHREADS][32 * 1024];
    stack_t ss = {.ss_sp = (void *)altstacks[idx],
                  .ss_size = sizeof(altstacks[0]),
                  .ss_flags = 0};
    sigaltstack(&ss, NULL);

    g_settid[idx] = g_ctid[idx]; /* what SETTID wrote */
    g_ready[idx] = 1;

    /* poll loop until preempted to death: compute + a short sleep per
     * pass (Go's sysmon cadence) — the SIGURG lands asynchronously at an
     * arbitrary point of the pass */
    struct timespec ts;
    while (!g_stop[idx]) {
        clock_gettime(CLOCK_MONOTONIC, &ts);
        g_spun[idx]++;
        struct timespec d = {0, 500 * 1000};
        nanosleep(&d, NULL);
    }
    return 0;
}

static long my_clone(int (*fn)(void *), void *stack_top, void *arg,
                     volatile int *ctid) {
    void **sp = (void **)stack_top;
    *--sp = arg;
    *--sp = (void *)fn;
    long flags = CLONE_VM | CLONE_FS | CLONE_FILES | CLONE_SIGHAND |
                 CLONE_THREAD | CLONE_SYSVSEM | CLONE_CHILD_SETTID |
                 CLONE_CHILD_CLEARTID;
    long ret;
    register long r10 asm("r10") = (long)ctid; /* ctid */
    asm volatile("syscall\n\t"
                 "test %%rax, %%rax\n\t"
                 "jnz 1f\n\t"
                 "pop %%rax\n\t"
                 "pop %%rdi\n\t"
                 "call *%%rax\n\t"
                 "mov %%rax, %%rdi\n\t"
                 "mov $60, %%rax\n\t"
                 "syscall\n\t"
                 "1:"
                 : "=a"(ret)
                 : "0"(56L), "D"(flags), "S"(sp), "d"(0), "r"(r10)
                 : "rcx", "r11", "memory");
    return ret;
}

int main(void) {
    setvbuf(stdout, NULL, _IONBF, 0);
    signal(SIGURG, urg_handler);

    long vtids[NTHREADS];
    for (int i = 0; i < NTHREADS; i++) {
        void *stk = mmap(NULL, STACK_SZ, PROT_READ | PROT_WRITE,
                         MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
        if (stk == MAP_FAILED)
            return 1;
        g_stackbase[i] = (char *)stk;
        g_args[i].idx = i;
        vtids[i] = my_clone(worker, (char *)stk + STACK_SZ, &g_args[i],
                            &g_ctid[i]);
        if (vtids[i] <= 0) {
            printf("clone %d failed %ld\n", i, vtids[i]);
            return 1;
        }
    }

    for (int i = 0; i < NTHREADS; i++)
        while (!g_ready[i])
            usleep(1000);

    /* the SETTID word must carry the VIRTUAL tid (the id this world
     * speaks), not the host kernel's */
    int settid_ok = 1;
    for (int i = 0; i < NTHREADS; i++)
        if (g_settid[i] != (int)vtids[i])
            settid_ok = 0;
    printf("settid ok %d\n", settid_ok);

    /* async preemption: SIGURG by virtual tid at spinning threads.
     * Standard signals coalesce, so (like the Go runtime's preemption
     * loop) keep resending until the target observes enough. */
    long pid = rsys(SYS_getpid_, 0, 0, 0, 0, 0);
    for (int i = 0; i < NTHREADS; i++)
        for (int tries = 0; g_sigs[i] < NPREEMPT && tries < 1000; tries++) {
            long r = rsys(SYS_tgkill_, pid, vtids[i], SIGURG, 0, 0);
            if (r != 0) {
                printf("tgkill(%ld) -> %ld\n", vtids[i], r);
                return 1;
            }
            usleep(2000);
        }

    /* ctid join (Go's thread join): wait for the kernel's cleartid */
    for (int i = 0; i < NTHREADS; i++) {
        int v;
        while ((v = g_ctid[i]) != 0)
            rsys(SYS_futex_, (long)&g_ctid[i], FUTEX_WAIT_, v, 0, 0);
    }
    printf("joined %d\n", NTHREADS);
    int sig_ok = 1;
    for (int i = 0; i < NTHREADS; i++)
        if (g_sigs[i] < NPREEMPT)
            sig_ok = 0;
    printf("preempts ok %d\n", sig_ok);
    int spun_ok = 1;
    for (int i = 0; i < NTHREADS; i++)
        if (g_spun[i] <= 0)
            spun_ok = 0;
    printf("spun ok %d\n", spun_ok);
    printf("go patterns all ok\n");
    return 0;
}
