/* Exercises the file-system story of managed processes: native file I/O
 * sandboxed into the per-host data dir (the kernel chdirs us there, like
 * the reference's SHADOW_WORKING_DIR), and the virtualized deterministic
 * /dev/urandom (reference regular_file.c special paths). */
#define _GNU_SOURCE
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#define CHECK(name, cond)                                                      \
    printf("%s %s\n", (cond) ? "PASS" : "FAIL", name)

int main(void) {
    /* native relative I/O lands in the sandbox cwd */
    FILE *f = fopen("guest_out.txt", "w");
    CHECK("fopen_w", f != NULL);
    CHECK("fwrite", fputs("written by guest", f) >= 0);
    fclose(f);
    char buf[64] = {0};
    f = fopen("guest_out.txt", "r");
    CHECK("fopen_r", f != NULL);
    CHECK("fread", fgets(buf, sizeof(buf), f) != NULL);
    CHECK("file_data", strcmp(buf, "written by guest") == 0);
    fclose(f);

    struct stat st;
    CHECK("stat", stat("guest_out.txt", &st) == 0 && st.st_size == 16);
    CHECK("mkdir", mkdir("subdir", 0755) == 0);
    CHECK("access", access("guest_out.txt", R_OK | W_OK) == 0);

    /* virtual /dev/urandom: deterministic per host seed */
    int rfd = open("/dev/urandom", O_RDONLY);
    CHECK("urandom_open", rfd >= 3); /* a virtual fd (lowest-free real number) */
    unsigned char rnd[16];
    CHECK("urandom_read", read(rfd, rnd, sizeof(rnd)) == (ssize_t)sizeof(rnd));
    printf("urand ");
    for (unsigned i = 0; i < sizeof(rnd); i++)
        printf("%02x", rnd[i]);
    printf("\n");
    /* writes are accepted and ignored */
    CHECK("urandom_close", close(rfd) == 0);

    int rfd2 = open("/dev/random", O_RDONLY);
    unsigned char rnd2[16];
    CHECK("random_read", read(rfd2, rnd2, sizeof(rnd2)) == (ssize_t)sizeof(rnd2));
    CHECK("streams_differ", memcmp(rnd, rnd2, sizeof(rnd)) != 0);
    close(rfd2);

    /* /dev/null stays native */
    int nfd = open("/dev/null", O_WRONLY);
    CHECK("devnull", nfd >= 0 && nfd < 1000 && write(nfd, "x", 1) == 1);
    close(nfd);

    unlink("guest_out.txt");
    CHECK("unlink", access("guest_out.txt", F_OK) != 0);
    rmdir("subdir");
    return 0;
}
