/* Round-2 syscall-breadth guest: asserts native-Linux semantics for the
 * newly trapped deterministic-view syscalls (affinity, rlimits, prctl,
 * statx/newfstatat, getdents64 via readdir, pread/pwrite, times/rusage,
 * sendmmsg, blocked-signal pending delivery). Prints "ok <name>" lines
 * the paired test checks, exactly like breadth_guest.c. */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <dirent.h>
#include <netinet/in.h>
#include <errno.h>
#include <fcntl.h>
#include <sched.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/prctl.h>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/times.h>
#include <time.h>
#include <unistd.h>

static int failures = 0;
#define CHECK(name, cond)                                                     \
    do {                                                                      \
        if (!(cond)) {                                                        \
            printf("FAIL %s (errno=%d)\n", name, errno);                      \
            failures++;                                                       \
        } else                                                                \
            printf("ok %s\n", name);                                          \
    } while (0)

static volatile sig_atomic_t got_usr1 = 0;
static void on_usr1(int s) { (void)s; got_usr1 = 1; }

int main(void) {
    /* deterministic 1-CPU topology */
    cpu_set_t set;
    CPU_ZERO(&set);
    CHECK("sched_getaffinity", sched_getaffinity(0, sizeof(set), &set) == 0 &&
                                   CPU_COUNT(&set) == 1 && CPU_ISSET(0, &set));
    CHECK("sched_setaffinity", sched_setaffinity(0, sizeof(set), &set) == 0);
    CHECK("nprocs", sysconf(_SC_NPROCESSORS_ONLN) >= 1);

    /* deterministic rlimits, settable */
    struct rlimit rl;
    CHECK("getrlimit_nofile",
          getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur == 1024);
    rl.rlim_cur = 512;
    CHECK("setrlimit_nofile", setrlimit(RLIMIT_NOFILE, &rl) == 0);
    CHECK("getrlimit_round_trip",
          getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur == 512);

    /* prctl: benign native, dangerous refused */
    CHECK("prctl_name", prctl(PR_SET_NAME, "breadth2", 0, 0, 0) == 0);
    CHECK("prctl_seccomp_refused",
          prctl(22 /*PR_SET_SECCOMP*/, 1, 0, 0, 0) == -1 && errno == EPERM);

    /* file breadth in the sandbox cwd: statx/newfstatat/getdents/pread */
    int fd = open("breadth2.dat", O_CREAT | O_RDWR | O_TRUNC, 0644);
    CHECK("open_rel", fd >= 0);
    CHECK("pwrite", pwrite(fd, "hello-breadth", 13, 7) == 13);
    char pb[16] = {0};
    CHECK("pread", pread(fd, pb, 13, 7) == 13 && memcmp(pb, "hello-breadth", 13) == 0);
    struct stat st;
    CHECK("newfstatat",
          fstatat(AT_FDCWD, "breadth2.dat", &st, 0) == 0 && st.st_size == 20);
    struct statx sx;
    CHECK("statx",
          syscall(SYS_statx, AT_FDCWD, "breadth2.dat", 0, 0x7ff, &sx) == 0 &&
              S_ISREG(sx.stx_mode));
    close(fd);

    int found = 0;
    DIR *d = opendir(".");
    if (d) {
        struct dirent *e;
        while ((e = readdir(d)) != NULL)
            if (strcmp(e->d_name, "breadth2.dat") == 0)
                found = 1;
        closedir(d);
    }
    CHECK("getdents64", found);

    /* statx on a virtual fd (socket) */
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    CHECK("newfstatat_vfd",
          fstatat(s, "", &st, AT_EMPTY_PATH) == 0 && S_ISSOCK(st.st_mode));
    close(s);

    /* deterministic process clocks */
    struct tms t1, t2;
    clock_t a = times(&t1);
    struct timespec dly = {0, 40 * 1000000};
    nanosleep(&dly, NULL);
    clock_t b = times(&t2);
    CHECK("times_advances_sim", b > a && (b - a) >= 3 && (b - a) <= 6);
    struct rusage ru;
    CHECK("getrusage", getrusage(RUSAGE_SELF, &ru) == 0 && ru.ru_maxrss == 4096);

    /* blocked signals stay pending; delivery on unblock */
    struct sigaction sa = {0};
    sa.sa_handler = on_usr1;
    sigaction(SIGUSR1, &sa, NULL);
    sigset_t blk, old;
    sigemptyset(&blk);
    sigaddset(&blk, SIGUSR1);
    sigprocmask(SIG_BLOCK, &blk, &old);
    kill(getpid(), SIGUSR1);
    struct timespec d2 = {0, 10 * 1000000};
    nanosleep(&d2, NULL);
    CHECK("blocked_signal_pending", got_usr1 == 0);
    sigprocmask(SIG_UNBLOCK, &blk, NULL);
    nanosleep(&d2, NULL);
    CHECK("unblock_delivers", got_usr1 == 1);

    /* sendmmsg over a simulated UDP socket pair */
    int tx = socket(AF_INET, SOCK_DGRAM, 0);
    int rx = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in addr = {0};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(9099);
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    bind(rx, (struct sockaddr *)&addr, sizeof(addr));
    addr.sin_addr.s_addr = htonl(0x7f000001);
    struct mmsghdr mv[2] = {0};
    struct iovec iov[2];
    iov[0].iov_base = "aa";
    iov[0].iov_len = 2;
    iov[1].iov_base = "bbb";
    iov[1].iov_len = 3;
    for (int i = 0; i < 2; i++) {
        mv[i].msg_hdr.msg_iov = &iov[i];
        mv[i].msg_hdr.msg_iovlen = 1;
        mv[i].msg_hdr.msg_name = &addr;
        mv[i].msg_hdr.msg_namelen = sizeof(addr);
    }
    int nm = (int)syscall(SYS_sendmmsg, tx, mv, 2, 0);
    char rb[8];
    long r1 = recv(rx, rb, sizeof(rb), 0);
    long r2 = recv(rx, rb, sizeof(rb), 0);
    CHECK("sendmmsg", nm == 2 && mv[0].msg_len == 2 && mv[1].msg_len == 3 &&
                          r1 == 2 && r2 == 3);
    close(tx);
    close(rx);

    if (failures == 0)
        printf("breadth2 all ok\n");
    return failures == 0 ? 0 : 1;
}
