"""Dynamic runahead (reference: runahead.rs:43-56, use_dynamic_runahead):
the window grows to the minimum latency actually used. On a graph whose
minimum edge latency (1 ms) belongs to links no traffic uses, while all
real paths are 20 ms, dynamic mode should cover ~20x more simulated time
per round with identical results."""

import dataclasses

import jax.numpy as jnp

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, run_rounds_scan
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.phold import PholdModel
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC


def _setup(num_hosts=8):
    # nodes 0,1 carry the hosts and talk over 20ms; nodes 2,3 have the
    # 1ms minimum-latency edge but host no traffic
    gml = "\n".join(
        [
            "graph [",
            "  directed 0",
            *[f"  node [ id {i} ]" for i in range(4)],
            '  edge [ source 0 target 0 latency "20 ms" ]',
            '  edge [ source 1 target 1 latency "20 ms" ]',
            '  edge [ source 0 target 1 latency "20 ms" ]',
            '  edge [ source 2 target 3 latency "1 ms" ]',
            '  edge [ source 2 target 2 latency "1 ms" ]',
            '  edge [ source 3 target 3 latency "1 ms" ]',
            "]",
        ]
    )
    graph = NetworkGraph.from_gml(gml)
    host_node = [i % 2 for i in range(num_hosts)]
    tables = compute_routing(graph).with_hosts(host_node)
    assert graph.min_latency_ns() == NS_PER_MS
    return graph, tables


def _run(dynamic: bool, rounds: int):
    graph, tables = _setup()
    cfg = EngineConfig(
        num_hosts=8,
        queue_capacity=32,
        runahead_ns=graph.min_latency_ns(),
        use_dynamic_runahead=dynamic,
        # this suite pins the dynamic-runahead mechanism in isolation:
        # the engine gates adaptive windows off under dynamic runahead
        # (window width is semantics-bearing there), but the STATIC
        # baseline leg would still be widened by the adaptive LBTS bound
        # on exactly this topology (tests/test_adaptive_window.py)
        adaptive_window=False,
    )
    model = PholdModel(num_hosts=8, min_delay_ns=NS_PER_MS, max_delay_ns=5 * NS_PER_MS)
    st = init_state(cfg, model.init())
    st = bootstrap(st, model, cfg)
    end = jnp.asarray(100 * NS_PER_SEC, jnp.int64)
    st = run_rounds_scan(st, end, rounds, model, tables, cfg)
    return st


def test_dynamic_window_covers_more_time():
    static = _run(False, 64)
    dyn = _run(True, 64)
    # same per-round drain semantics, but the dynamic window grows to the
    # 20ms used latency after the first exchange
    assert int(dyn.now) > 5 * int(static.now)
    assert int(dyn.min_used_lat) == 20 * NS_PER_MS


def test_dynamic_matches_static_results():
    """Event totals at a fixed horizon agree between modes (delivery-time
    clamping keeps both schedules within the same semantics)."""
    graph, tables = _setup()
    end = jnp.asarray(2 * NS_PER_SEC, jnp.int64)
    totals = []
    for dynamic, rounds in ((False, 2200), (True, 160)):
        cfg = EngineConfig(
            num_hosts=8,
            queue_capacity=32,
            runahead_ns=graph.min_latency_ns(),
            use_dynamic_runahead=dynamic,
            adaptive_window=False,
        )
        model = PholdModel(num_hosts=8, min_delay_ns=NS_PER_MS, max_delay_ns=5 * NS_PER_MS)
        st = init_state(cfg, model.init())
        st = bootstrap(st, model, cfg)
        st = run_rounds_scan(st, end, rounds, model, tables, cfg)
        assert int(st.now) >= int(end)
        totals.append(int(jnp.sum(st.events_handled)))
    # phold balls bounce once per hop; totals must be close (clamp shifts
    # a few deliveries at the horizon) — require within 2%
    a, b = totals
    assert abs(a - b) <= max(2, a // 50), totals
