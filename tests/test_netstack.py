"""Token-bucket relays + CoDel AQM: unit tests of the closed-form shaping
math against the integer reference, plus full-engine conformance with the
netstack enabled (the analogue of the reference's relay/token-bucket/CoDel
unit tests, src/main/network/relay/token_bucket.rs tests and
router/codel_queue.rs tests, and its determinism double-runs)."""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu import equeue, netstack
from shadow_tpu.cpu_ref import CpuRefPhold
from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, round_body_debug, run_until
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models import PholdModel
from shadow_tpu.cpu_ref.netstack_ref import CoDelRef, TokenBucketRef
from shadow_tpu.netstack import (
    CODEL_INTERVAL_NS,
    CODEL_TARGET_NS,
    MTU_BYTES,
    REFILL_INTERVAL_NS,
)
from shadow_tpu.simtime import NS_PER_MS


def test_tb_depart_matches_integer_reference():
    rng_py = random.Random(3)
    for refill in [0, 100, 1500, 12500]:
        ref = TokenBucketRef(refill)
        tokens = jnp.asarray([ref.tokens], jnp.int64)
        last = jnp.asarray([0], jnp.int64)
        refill_a = jnp.asarray([refill], jnp.int64)
        now = 0
        for _ in range(200):
            now += rng_py.randrange(0, 3 * REFILL_INTERVAL_NS)
            size = rng_py.randrange(0, MTU_BYTES + 1)
            dep, tokens, last = netstack.tb_depart(
                tokens, last, refill_a, jnp.asarray([now], jnp.int64),
                jnp.asarray([size], jnp.int64), jnp.asarray([True]),
            )
            dep_ref = ref.depart(now, size)
            assert int(dep[0]) == dep_ref, (refill, now, size)
            assert int(tokens[0]) == ref.tokens
            assert int(last[0]) == ref.last
            # a departing packet never leaves before presentation
            assert dep_ref >= now
            # once the bucket served it, the next packet can't depart earlier
            now = max(now, dep_ref)


def test_tb_rate_limit_long_run():
    # sustained back-to-back sends settle at exactly refill bytes/interval
    refill = 1000
    tb = TokenBucketRef(refill)
    now, sent = 0, 0
    for _ in range(100):
        dep = tb.depart(now, 500)
        now = dep
        sent += 500
    # 50_000 bytes at 1000/interval -> ~50 intervals (minus initial burst)
    expected_intervals = (sent - (refill + MTU_BYTES)) / refill
    assert now >= (expected_intervals - 1) * REFILL_INTERVAL_NS
    assert now <= (expected_intervals + 1) * REFILL_INTERVAL_NS


def test_codel_vector_matches_integer_reference():
    rng_py = random.Random(9)
    ref = CoDelRef()
    net = netstack.create(1)
    drops_v, drops_r = 0, 0
    now = 0
    for i in range(400):
        now += rng_py.randrange(1, 20) * NS_PER_MS
        # alternate phases of overload (high sojourn) and drain
        overload = (i // 50) % 2 == 0
        sojourn = (
            rng_py.randrange(CODEL_TARGET_NS, 4 * CODEL_TARGET_NS)
            if overload
            else rng_py.randrange(0, CODEL_TARGET_NS // 2)
        )
        backlog = 5 * MTU_BYTES if overload else 0
        net = net.replace(rx_backlog_bytes=jnp.asarray([backlog], jnp.int64))
        drop, net = netstack.codel_dequeue(
            net, jnp.asarray([now], jnp.int64), jnp.asarray([sojourn], jnp.int64),
            jnp.asarray([True]),
        )
        rdrop = ref.dequeue(now, sojourn, backlog)
        assert bool(drop[0]) == rdrop, i
        drops_v += bool(drop[0])
        drops_r += rdrop
    assert drops_v == drops_r
    assert drops_v > 0  # the overload phases actually triggered the AQM


def test_codel_starts_dropping_after_interval():
    ref = CoDelRef()
    t = 0
    drops = []
    for i in range(30):
        t += 10 * NS_PER_MS
        drops.append(ref.dequeue(t, 2 * CODEL_TARGET_NS, 10 * MTU_BYTES))
    # no drop before a full INTERVAL above target, then drops begin
    first_drop = drops.index(True)
    assert first_drop * 10 * NS_PER_MS >= CODEL_INTERVAL_NS
    assert sum(drops) >= 2  # control law keeps dropping under sustained load


def _net_setup(num_hosts=6, seed=13, refill_bytes=2000, ball_bytes=1200,
               bootstrap_end_ns=0, loss=0.0):
    n_nodes = 3
    rng_py = random.Random(seed)
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "500 us" packet_loss {loss} ]')
    for i in range(n_nodes):
        for j in range(i + 1, n_nodes):
            lines.append(
                f'  edge [ source {i} target {j} latency "{rng_py.randrange(1, 6)} ms" packet_loss {loss} ]'
            )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    host_node = [i % n_nodes for i in range(num_hosts)]
    tables = compute_routing(graph, block=8).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=128,
        outbox_capacity=8,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
        use_netstack=True,
        bootstrap_end_ns=bootstrap_end_ns,
    )
    model = PholdModel(
        num_hosts=num_hosts, min_delay_ns=1 * NS_PER_MS, max_delay_ns=6 * NS_PER_MS,
        ball_bytes=ball_bytes,
    )
    tx = rx = refill_bytes
    st = init_state(cfg, model.init(), tx_bytes_per_interval=tx, rx_bytes_per_interval=rx)
    st = bootstrap(st, model, cfg)
    return cfg, model, tables, host_node, st, tx, rx


def _engine_trace_run(st, end_time, model, tables, cfg):
    trace = []
    while True:
        start = int(jnp.min(equeue.next_time(st.queue)))
        if start >= end_time:
            break
        window_end = min(start + cfg.runahead_ns, end_time)
        st = round_body_debug(st, window_end, model, tables, cfg, trace=trace)
    return st, trace


@pytest.mark.parametrize("loss", [0.0, 0.15])
def test_engine_netstack_matches_cpu_reference(loss):
    cfg, model, tables, host_node, st, tx, rx = _net_setup(loss=loss)
    end = 80 * NS_PER_MS

    ref = CpuRefPhold(cfg, model, tables, host_node,
                      tx_bytes_per_interval=tx, rx_bytes_per_interval=rx)
    ref.bootstrap()
    ref.run_until(end)

    st, trace = _engine_trace_run(st, end, model, tables, cfg)

    key = lambda e: (e[0], e[1])
    assert sorted(trace, key=key) == sorted(ref.trace, key=key)
    assert len(trace) > 20

    assert [int(x) for x in st.model.recv_count] == ref.recv
    assert [int(x) for x in st.model.send_count] == ref.send
    assert [int(x) for x in st.packets_sent] == ref.packets_sent
    assert [int(x) for x in st.packets_dropped] == ref.packets_dropped
    assert [int(x) for x in st.seq] == ref.seq
    assert [int(x) for x in st.rng_counter] == ref.ctr
    assert [int(x) for x in st.net.bytes_sent] == ref.bytes_sent
    assert [int(x) for x in st.net.bytes_recv] == ref.bytes_recv
    assert [int(x) for x in st.net.codel_dropped] == ref.codel_dropped

    for h in range(cfg.num_hosts):
        dev = equeue.debug_sorted_events(st.queue, h)
        assert dev == ref.queue_contents(h), f"host {h}"

    # shaping actually happened: some packet was delayed past raw latency
    assert int(np.asarray(st.net.bytes_recv).sum()) > 0


def test_netstack_jit_matches_debug_and_shapes_traffic():
    cfg, model, tables, host_node, st0, tx, rx = _net_setup(seed=29, refill_bytes=1500)
    end = 60 * NS_PER_MS

    st_debug, _ = _engine_trace_run(st0, end, model, tables, cfg)
    st_jit = run_until(st0, end, model, tables, cfg, rounds_per_chunk=8)

    for name in ["seq", "rng_counter", "packets_sent", "packets_dropped"]:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_jit, name)), np.asarray(getattr(st_debug, name))
        )
    for name in ["bytes_sent", "bytes_recv", "codel_dropped", "rx_backlog_bytes",
                 "tx_tokens", "rx_tokens", "codel_count"]:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_jit.net, name)), np.asarray(getattr(st_debug.net, name))
        )
    for h in range(cfg.num_hosts):
        assert equeue.debug_sorted_events(st_jit.queue, h) == equeue.debug_sorted_events(
            st_debug.queue, h
        )


def test_netstack_unlimited_is_noop():
    # refill 0 = unshaped: identical timeline to use_netstack=False
    cfg_on, model, tables, host_node, st_on, _, _ = _net_setup(refill_bytes=0)
    import dataclasses

    cfg_off = dataclasses.replace(cfg_on, use_netstack=False)
    st_off = bootstrap(init_state(cfg_off, model.init()), model, cfg_off)

    end = 50 * NS_PER_MS
    _, trace_on = _engine_trace_run(st_on, end, model, tables, cfg_on)
    _, trace_off = _engine_trace_run(st_off, end, model, tables, cfg_off)
    assert trace_on == trace_off


def test_bootstrap_period_exempt_from_shaping():
    # with the whole run inside the bootstrap window, shaping is off
    cfg_b, model, tables, host_node, st_b, tx, rx = _net_setup(
        refill_bytes=800, bootstrap_end_ns=10_000 * NS_PER_MS
    )
    cfg_u, _, _, _, st_u, _, _ = _net_setup(refill_bytes=0)
    end = 40 * NS_PER_MS
    _, trace_b = _engine_trace_run(st_b, end, model, tables, cfg_b)
    _, trace_u = _engine_trace_run(st_u, end, model, tables, cfg_u)
    assert trace_b == trace_u


def test_tb_depart_lanes_equals_sequential():
    """The closed-form multi-lane conforming-remove must EXACTLY equal L
    sequential tb_depart calls — including the subtle case where an
    earlier lane's interval refill leaves enough balance for a later
    lane to depart at `now` despite a positive raw prefix deficit."""
    import random

    import numpy as np

    from shadow_tpu.netstack import tb_depart, tb_depart_lanes

    rng = random.Random(5)
    H, L = 16, 5
    for trial in range(20):
        tokens = jnp.asarray([rng.randrange(0, 4000) for _ in range(H)], jnp.int64)
        last = jnp.asarray([rng.randrange(0, 3) * 1_000_000 for _ in range(H)], jnp.int64)
        refill = jnp.asarray(
            [rng.choice([0, 1250, 2500, 12500]) for _ in range(H)], jnp.int64
        )
        now = jnp.asarray(
            [rng.randrange(2, 9) * 1_000_000 + rng.randrange(0, 999_999) for _ in range(H)],
            jnp.int64,
        )
        sizes = jnp.asarray(
            [[rng.choice([40, 590, 1500, 1540]) for _ in range(L)] for _ in range(H)],
            jnp.int64,
        )
        charge = jnp.asarray(
            [[rng.random() < 0.7 for _ in range(L)] for _ in range(H)], bool
        )
        # sequential reference
        tok, la = tokens, last
        seq_dep = []
        for i in range(L):
            d, tok, la = tb_depart(tok, la, refill, now, sizes[:, i], charge[:, i])
            seq_dep.append(d)
        deps, tok2, la2 = tb_depart_lanes(tokens, last, refill, now, sizes, charge)
        np.testing.assert_array_equal(
            np.stack([np.asarray(x) for x in seq_dep], axis=1),
            np.asarray(deps),
            err_msg=f"departs trial {trial}",
        )
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok2), f"tokens {trial}")
        np.testing.assert_array_equal(np.asarray(la), np.asarray(la2), f"last {trial}")
