"""Fault-tolerant run loop (docs/robustness.md): deterministic
checkpoint/restore and rollback-and-regrow capacity recovery.

The contract under test is the determinism invariant extended across
faults: a run interrupted at a chunk boundary and resumed from its
checkpoint must reach a final SimState (including the tracker plane)
bit-identical to an uninterrupted run, and a run that recovers from a
capacity blowup by regrowing the saturated buffer must be leaf-exact to
a run that started with the larger capacity."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from test_pipeline import _assert_leaves_exact, _phold_world
from test_pump import _world as _tgen_world

from shadow_tpu.engine.round import CapacityError, RunInterrupted, run_until
from shadow_tpu.engine.sharded import AXIS, ShardedRunner
from shadow_tpu.engine.state import grow_state, state_from_host, state_to_host
from shadow_tpu.runtime.checkpoint import (
    CheckpointError,
    CheckpointManager,
    InterruptGuard,
    StateTap,
    load_checkpoint,
    peek_checkpoint_meta,
    save_checkpoint,
)
from shadow_tpu.runtime.recovery import (
    RecoveryPolicy,
    run_until_recovering,
)
from shadow_tpu.simtime import NS_PER_MS
from shadow_tpu.utils.tracker import Tracker


def test_state_host_roundtrip():
    """state_to_host/state_from_host is lossless, including the typed
    PRNG key leaves that numpy cannot hold natively."""
    _cfg, _model, _tables, st0 = _phold_world()
    host = state_to_host(st0)
    _assert_leaves_exact(st0, state_from_host(host, st0))


def test_checkpoint_file_roundtrip(tmp_path):
    cfg, model, tables, st0 = _phold_world()
    st = run_until(st0, 10 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state_to_host(st), {"fingerprint": "fp", "now_ns": 1})
    restored, meta = load_checkpoint(path, st0, "fp")
    _assert_leaves_exact(st, restored)
    assert meta["fingerprint"] == "fp"
    assert meta["queue_capacity"] == cfg.queue_capacity
    # the meta is peekable without loading the leaf arrays
    assert peek_checkpoint_meta(path)["num_leaves"] == meta["num_leaves"]
    with pytest.raises(CheckpointError, match="different config"):
        load_checkpoint(path, st0, "other-fp")


def test_checkpoint_template_shape_mismatch(tmp_path):
    """A checkpoint can only restore into the exact world it came from."""
    cfg, model, tables, st0 = _phold_world(num_hosts=6)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, state_to_host(st0), {"fingerprint": "fp"})
    _cfg2, _m2, _t2, other = _phold_world(num_hosts=4)
    with pytest.raises(CheckpointError):
        load_checkpoint(path, other, "fp")


def _interrupt_then_resume(cfg, model, tables, st0, end, ckpt_dir,
                           interval_ns, interrupt_at_ns, rpc=4):
    """Drive run_until with a checkpoint tap until the (deterministic)
    test interrupt fires, then restore the newest checkpoint and run it
    to completion. Returns the resumed final state."""
    ck = CheckpointManager(str(ckpt_dir), interval_ns, "fp")
    guard = InterruptGuard(test_interrupt_at_ns=interrupt_at_ns)
    tap = StateTap(checkpoints=ck, guard=guard)
    with pytest.raises(RunInterrupted):
        run_until(st0, end, model, tables, cfg, rounds_per_chunk=rpc,
                  on_state=tap)
    path = CheckpointManager.latest_path(str(ckpt_dir))
    assert path is not None
    restored, meta = load_checkpoint(path, st0, "fp")
    assert 0 < meta["now_ns"] < end
    return run_until(restored, end, model, tables, cfg, rounds_per_chunk=rpc)


@pytest.mark.parametrize("tracker_on", [False, True])
def test_interrupt_resume_bit_exact_phold(tmp_path, tracker_on):
    """Kill-mid-run → resume reaches a bit-identical final state — with
    the device tracker plane both off and on (the tracker leaves ride
    the checkpoint and must stay trajectory-exact too)."""
    cfg, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg, tracker=tracker_on)
    end = 40 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    resumed = _interrupt_then_resume(
        cfg, model, tables, st0, end, tmp_path,
        interval_ns=8 * NS_PER_MS, interrupt_at_ns=20 * NS_PER_MS,
    )
    assert int(resumed.events_handled.sum()) > 0
    _assert_leaves_exact(straight, resumed)


@pytest.mark.slow
@pytest.mark.parametrize("engine", ["plain", "pump"])
def test_interrupt_resume_bit_exact_tgen(tmp_path, engine):
    """Resume bit-exactness on the flagship TCP workload, per engine
    (slow tier: each engine compiles its own chunk executable twice; the
    tier-1 resume coverage is the phold tracker-on/off pair above)."""
    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    cfg = (
        dataclasses.replace(cfg0, engine="plain")
        if engine == "plain"
        else dataclasses.replace(cfg0, engine=engine, pump_k=3)
    )
    end = 30 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=2)
    resumed = _interrupt_then_resume(
        cfg, model, tables, st0, end, tmp_path,
        interval_ns=4 * NS_PER_MS, interrupt_at_ns=10 * NS_PER_MS, rpc=2,
    )
    assert int(resumed.events_handled.sum()) > 0
    _assert_leaves_exact(straight, resumed)


@pytest.mark.slow
def test_interrupt_resume_bit_exact_tgen_megakernel(tmp_path):
    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    cfg = dataclasses.replace(cfg0, engine="megakernel", pump_k=3)
    end = 30 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=2)
    resumed = _interrupt_then_resume(
        cfg, model, tables, st0, end, tmp_path,
        interval_ns=4 * NS_PER_MS, interrupt_at_ns=10 * NS_PER_MS, rpc=2,
    )
    _assert_leaves_exact(straight, resumed)


@pytest.mark.slow
def test_interrupt_resume_bit_exact_sharded(tmp_path):
    """Resume through the sharded driver: the checkpoint is written from
    the (gathered) sharded state and restored into a re-sharded run."""
    import numpy as np
    from jax.sharding import Mesh

    cfg, model, tables, st0 = _phold_world(num_hosts=8)
    end = 40 * NS_PER_MS
    mesh = Mesh(np.array(jax.devices()[:4]), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=4)
    straight = runner.run_until(st0, end)

    ck = CheckpointManager(str(tmp_path), 8 * NS_PER_MS, "fp")
    guard = InterruptGuard(test_interrupt_at_ns=20 * NS_PER_MS)
    runner2 = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=4)
    with pytest.raises(RunInterrupted):
        runner2.run_until(st0, end, on_state=StateTap(checkpoints=ck, guard=guard))
    restored, _meta = load_checkpoint(
        CheckpointManager.latest_path(str(tmp_path)), st0, "fp"
    )
    runner3 = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=4)
    resumed = runner3.run_until(restored, end)
    _assert_leaves_exact(straight, resumed)


def test_grow_state_preserves_contents():
    cfg, model, tables, st0 = _phold_world()
    st = run_until(st0, 10 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4)
    grown = grow_state(st, queue_capacity=cfg.queue_capacity * 2,
                       outbox_capacity=16)
    assert grown.queue.capacity == cfg.queue_capacity * 2
    assert grown.outbox.valid.shape[1] == 16
    old = cfg.queue_capacity
    assert jnp.array_equal(grown.queue.time[:, :old], st.queue.time[:, :old])
    assert jnp.array_equal(grown.queue.count, st.queue.count)
    assert jnp.array_equal(grown.queue.head_time, st.queue.head_time)
    # new slots read as canonical free slots
    assert bool(jnp.all(grown.queue.time[:, old:] == grown.queue.time.max()))
    with pytest.raises(ValueError, match="shrink"):
        grow_state(st, queue_capacity=old - 1)


def test_regrow_recovers_leaf_exact():
    """A workload sized to overflow the seed queue capacity completes via
    rollback-and-regrow, the recovery is visible in the tracker fold, and
    the trajectory is leaf-exact vs a run that STARTED with the grown
    capacity."""
    cfg, model, tables, st0 = _phold_world(queue_capacity=2)
    end = 60 * NS_PER_MS
    with pytest.raises(CapacityError):
        run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)

    tracker = Tracker()
    final, recoveries = run_until_recovering(
        st0, end, model, tables, cfg, rounds_per_chunk=4, tracker=tracker,
        policy=RecoveryPolicy(max_recoveries=4, snapshot_interval_chunks=2),
    )
    assert len(recoveries) >= 1
    assert recoveries[0]["queue_overflow"] > 0
    assert tracker.stats_dict()["recoveries"] == recoveries
    grown_cap = final.queue.capacity
    assert grown_cap > 2

    cfg2, model2, tables2, st2 = _phold_world(queue_capacity=grown_cap)
    reference = run_until(st2, end, model2, tables2, cfg2, rounds_per_chunk=4)
    _assert_leaves_exact(reference, final)


def test_recovery_budget_exhausted_raises():
    """max_recoveries=0 is fail-fast (--no-recover): the original
    CapacityError surfaces unchanged."""
    cfg, model, tables, st0 = _phold_world(queue_capacity=2)
    with pytest.raises(CapacityError):
        run_until_recovering(
            st0, 60 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4,
            policy=RecoveryPolicy(max_recoveries=0),
        )


@pytest.mark.slow
def test_sharded_capacity_error_names_shard():
    """The sharded probe arrives mesh-summed; the CapacityError must
    still say WHICH shard saturated (per-shard overflow fetched on the
    failure path only)."""
    import numpy as np
    from jax.sharding import Mesh

    cfg, model, tables, st0 = _phold_world(num_hosts=8)
    mesh = Mesh(np.array(jax.devices()[:4]), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=4)
    # seed overflow on a host row owned by shard 2 (rows 4-5 of 8 over 4)
    bad = st0.replace(
        queue=st0.queue.replace(overflow=st0.queue.overflow.at[4].add(3))
    )
    with pytest.raises(CapacityError, match="shard 2") as ei:
        runner.run_until(bad, 400 * NS_PER_MS)
    assert "shard 2" in (ei.value.shard_detail or "")
    assert "shard 0" not in (ei.value.shard_detail or "")
