"""Name-service API tests (reference: shim_api_addrinfo.c,
shim_api_ifaddrs.c, dns.c registry + reverse resolution, src/test/ifaddrs
paired suite)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def dns_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "dns_guest"
    subprocess.run(["cc", "-O2", "-o", str(out), str(GUESTS / "dns_guest.c")], check=True)
    return str(out)


def test_dns_apis_under_shim(tmp_path, dns_bin):
    tables = compute_routing(two_node_graph()).with_hosts([0, 1])
    k = NetKernel(
        tables,
        host_names=["server", "client"],
        host_nodes=[0, 1],
        data_dir=tmp_path / "data",
    )
    p = k.add_process(
        ProcessSpec(
            host="client",
            args=[dns_bin, "server", "11.0.0.1", "11.0.0.2"],
        )
    )
    try:
        k.run(NS_PER_SEC)
    finally:
        k.shutdown()
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "dns all ok" in out
    assert "hostname=client" in out
    # hosts file exported for native consumption (dns.c:115 analogue)
    hosts = (tmp_path / "data" / "hosts").read_text()
    assert "11.0.0.1 server" in hosts and "11.0.0.2 client" in hosts
