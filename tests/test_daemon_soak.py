"""Chaos-driven SLO soak for the durable daemon (docs/service.md
"Daemon mode"; the robustness capstone of ISSUE 11).

100+ jobs across 3 tenants — roughly one simulated DAY of aggregate
sim-time — submitted to a spooled daemon while the chaos plane fires
daemon-kills (the process is SIGKILLed and restarted on the same spool,
repeatedly), journal-record corruption, cache-entry corruption, and a
persistent poison-job capacity fault. The acceptance bar:

  * ZERO lost jobs: every admitted job reaches a terminal, journaled
    status (done, or quarantined for the poison entry);
  * the queue drains via quarantine rather than collapse: only the
    poisoned entry's jobs may end non-done, and the daemon's exit after
    the final fault-free drain reflects the quarantine (non-zero), not
    a crash;
  * the persistent compile cache amortizes across restarts (the
    restarted daemons pay near-zero recompiles);
  * jobs/hour and cache-hit-rate are published (the numbers bench
    mirrors under detail.service).

A second scenario soaks the FLEET contract (docs/service.md "Running a
fleet"): two daemons on one spool, one SIGKILLed mid-batch — the
survivor must wait out the dead daemon's lease, journal the claim
steal, resume from the newest checkpoint, and finish every job with
sim-stats bit-exact to uninterrupted standalone runs.

Runs under the `soak` marker (registered in pyproject.toml), excluded
from tier-1 via `slow`. SHADOW_TPU_SOAK_JOBS overrides the job count.
"""

import json
import os
import subprocess
import sys

import pytest
import yaml

from shadow_tpu.runtime.cli_run import run_serve, run_submit

pytestmark = [pytest.mark.soak, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ~14 sim-minutes per job; 102 jobs ~= 23.8 simulated hours. Sparse
# phold traffic + adaptive windows keep each batch's wall cost small —
# this soaks the SERVICE (journal, restarts, quarantine, cache), not
# the engine.
SOAK_CONFIG = {
    "general": {
        "stop_time": "840 s",
        "heartbeat_interval": None,
        "checkpoint_interval": "200 s",
    },
    "network": {"graph": {"type": "1_gbit_switch"}},
    "experimental": {"rounds_per_chunk": 8, "recover": False},
    "hosts": {
        "peer": {
            "network_node_id": 0,
            "quantity": 4,
            "processes": [
                {
                    "path": "phold",
                    "args": {"min_delay": "200 ms", "max_delay": "2 s"},
                }
            ],
        }
    },
}

TENANTS = ("t1", "t2", "t3")
POISON_JOB = "t3.poison-s0"


def _submit_all(tmp_path, spool, total_jobs):
    """total_jobs spread over 3 tenants, 6 seeds per spec, plus one
    single-seed poison entry for t3."""
    per_spec = 6
    submitted = []
    n = 0
    i = 0
    while n < total_jobs - 1:
        tenant = TENANTS[i % len(TENANTS)]
        seeds = list(range(i * per_spec, i * per_spec + per_spec))
        spec = tmp_path / f"spec-{i:03d}.yaml"
        spec.write_text(
            yaml.safe_dump(
                {
                    "job": {
                        "tenant": tenant,
                        "name": f"e{i:03d}",
                        "seeds": seeds,
                        "config": SOAK_CONFIG,
                    }
                }
            )
        )
        assert run_submit(str(spool), str(spec)) == 0
        submitted.extend(f"{tenant}.e{i:03d}-s{s}" for s in seeds)
        n += len(seeds)
        i += 1
    poison = tmp_path / "poison.yaml"
    poison.write_text(
        yaml.safe_dump(
            {
                "job": {
                    "tenant": "t3",
                    "name": "poison",
                    "seeds": [0],
                    "config": SOAK_CONFIG,
                }
            }
        )
    )
    assert run_submit(str(spool), str(poison)) == 0
    submitted.append(POISON_JOB)
    return submitted


def _serve(spool, *faults, seed=0, timeout=1800):
    env = dict(os.environ)
    env.update(PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    args = [sys.executable, "-m", "shadow_tpu.cli", "serve", str(spool),
            "--drain", "--retry-max", "1", "--chaos-seed", str(seed),
            # the poison fault fires every attempt
            "--chaos-fault", f"capacity:target={POISON_JOB}:count=-1"]
    for f in faults:
        args += ["--chaos-fault", f]
    return subprocess.run(args, cwd=REPO_ROOT, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_soak_100_jobs_3_tenants_chaos(tmp_path):
    total_jobs = int(os.environ.get("SHADOW_TPU_SOAK_JOBS", "102"))
    spool = tmp_path / "spool"
    submitted = _submit_all(tmp_path, spool, total_jobs)
    assert len(submitted) >= total_jobs

    # chaos phase: each run is killed at a seeded, auto-drawn site;
    # journal and cache corruption ride along. Restart on the same
    # spool every time.
    kill_phases = [
        ("daemon-kill@auto:target=chunk", "spool-corrupt@3"),
        ("daemon-kill@1:target=batch-start", "cache-corrupt@0"),
        ("daemon-kill@0:target=checkpoint",),
        ("daemon-kill@auto:target=chunk",),
    ]
    crashes = 0
    for n, faults in enumerate(kill_phases):
        r = _serve(spool, *faults, seed=n)
        if r.returncode in (-9, 137):
            crashes += 1
        # a phase may also finish cleanly if the kill site was never
        # reached (e.g. the queue drained first) — that's fine

    # final fault-free drains (in-process, poison fault still injected
    # via the subprocess-only plan being absent -> the poison job now
    # RUNS CLEAN? No: quarantine must already have happened, or the job
    # simply completes — both are terminal; zero-lost is the invariant)
    for _ in range(3):
        rc = run_serve(str(spool), drain=True)
        m = json.loads((spool / "daemon-manifest.json").read_text())
        if m["daemon"]["outstanding_jobs"] == 0:
            break
    assert m["daemon"]["outstanding_jobs"] == 0, (
        f"queue failed to drain: {m['daemon']['outstanding_jobs']} "
        f"outstanding after the fault-free drains"
    )

    # ---- zero lost jobs: every admitted job is terminal in the journal
    recs = []
    for f in sorted((spool / "journal").glob("r*.json")):
        try:
            recs.append(json.loads(f.read_text()))
        except ValueError:
            continue  # a chaos-corrupted record; its admission recovered
    admitted = {j for r in recs if r.get("type") == "admit"
                for j in r.get("jobs", [])}
    terminal = {r.get("job"): r["type"][len("job-"):]
                for r in recs
                if r.get("type") in ("job-done", "job-failed",
                                     "job-quarantined")}
    assert set(submitted) <= admitted
    lost = admitted - set(terminal)
    assert not lost, f"lost jobs (admitted, never terminal): {sorted(lost)}"

    # ---- drain via quarantine, not collapse: only the poison entry may
    # end non-done (it ran its final attempts without the injected fault
    # in-process, so done is also acceptable — but nothing ELSE may fail)
    non_done = {j: s for j, s in terminal.items() if s != "done"}
    assert set(non_done) <= {POISON_JOB}, f"unexpected failures: {non_done}"

    # ---- every done job published standalone-format outputs
    sample = sorted(j for j in submitted if terminal.get(j) == "done")[:5]
    for name in sample:
        stats = json.loads(
            (spool / "jobs" / name / "sim-stats.json").read_text()
        )
        assert stats["events_handled"] > 0

    # ---- the SLO numbers exist and the cache amortized across restarts
    d = m["daemon"]
    assert d["jobs_per_hour"] is None or d["jobs_per_hour"] >= 0
    assert d["jobs_done_total"] >= len(submitted) - 1
    cache = m["compile_cache"]
    # the final drains ran entirely from the persistent cache unless the
    # corruption fault forced one recompile
    assert cache["hit_rate"] >= 0.5 or cache["compiles"] <= 2
    assert crashes >= 1, "the chaos phase must have killed the daemon"


# ---- fleet: SIGKILL one of two daemons, survivor reclaims the lease ----

# fast-checkpointing small world: enough chunks that the kill lands
# mid-batch with checkpoints on disk, small enough that standalone
# comparison runs stay cheap
FLEET_CONFIG = {
    "general": {
        "stop_time": "600 ms",
        "heartbeat_interval": None,
        "tracker": True,
        "checkpoint_interval": "20 ms",
    },
    "network": {"graph": {"type": "1_gbit_switch"}},
    "experimental": {"rounds_per_chunk": 4},
    "hosts": {
        "peer": {
            "network_node_id": 0,
            "quantity": 8,
            "processes": [
                {
                    "path": "phold",
                    "args": {"min_delay": "2 ms", "max_delay": "12 ms"},
                }
            ],
        }
    },
}


def _trajectory_stats(path) -> dict:
    """sim-stats.json modulo wall-clock and execution-shape counters
    (the test_daemon_cli.py comparison idiom): a daemon ensemble batch
    and a sharded standalone run legitimately differ in drain-iteration
    shape; every trajectory fact must not."""
    s = json.loads(path.read_text())
    s.pop("wall_seconds")
    s.pop("memory", None)
    if "tracker" in s:
        s["tracker"].pop("phases", None)
        for k in ("iters", "lanes_live", "occupancy"):
            s["tracker"].get("window", {}).pop(k, None)
    return s


def test_fleet_sigkill_lease_reclaim_bit_exact(tmp_path):
    """Acceptance: SIGKILL of either fleet daemon mid-batch is recovered
    by the survivor via lease expiry — claim steal journaled, batch
    resumed from the victim's newest checkpoint, zero lost jobs, zero
    double-claims, and outputs bit-exact vs standalone runs."""
    import signal
    import time

    spool = tmp_path / "spool"
    cache = tmp_path / "cache"
    jobs = [("alice", "a", (1, 2)), ("bob", "b", (3, 4))]
    for i, (tenant, name, seeds) in enumerate(jobs):
        spec = tmp_path / f"{tenant}.yaml"
        spec.write_text(yaml.safe_dump({
            "job": {"tenant": tenant, "name": name,
                    "seeds": list(seeds), "config": FLEET_CONFIG}
        }))
        assert run_submit(str(spool), str(spec)) == 0

    env = dict(os.environ)
    env.update(PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def serve(daemon_id):
        return subprocess.Popen(
            [sys.executable, "-m", "shadow_tpu.cli", "serve", str(spool),
             "--drain", "--poll-interval", "0.2", "--lease-s", "6",
             "--daemon-id", daemon_id, "--cache-dir", str(cache)],
            cwd=REPO_ROOT, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    # victim: kill the instant a checkpoint commits — mid-batch with a
    # held lease and a resumable trajectory on disk
    victim = serve("victim")
    deadline = time.monotonic() + 600
    killed = False
    while time.monotonic() < deadline:
        ckpts = list((spool / "batches").glob("*/ckpts/ckpt-*.npz"))
        if ckpts and victim.poll() is None:
            victim.send_signal(signal.SIGKILL)
            killed = True
            break
        time.sleep(0.1)
    assert killed, "victim never reached a checkpoint"
    assert victim.wait(timeout=60) in (-9, 137)
    claims = list((spool / "claims").glob("claim-*.json"))
    assert claims, "the dead daemon's claim must survive the kill"

    survivor = serve("survivor")
    out, _ = survivor.communicate(timeout=900)
    assert survivor.returncode == 0, out

    recs = []
    for f in sorted((spool / "journal").glob("r*.json")):
        recs.append(json.loads(f.read_text()))
    steals = [r for r in recs if r["type"] == "claim-steal"]
    assert steals and steals[0]["from_owner"] == "victim"
    assert steals[0]["owner"] == "survivor"
    done = [r["job"] for r in recs if r["type"] == "job-done"]
    expected = sorted(
        f"{t}.{n}-s{s}" for t, n, seeds in jobs for s in seeds
    )
    # exactly-once: zero lost AND zero double-claimed
    assert sorted(done) == expected
    assert not list((spool / "claims").glob("claim-*.json"))

    # bit-exact vs uninterrupted standalone runs, including the batch
    # that crossed the kill + resume
    from shadow_tpu.runtime.cli_run import run_from_config

    for tenant, name, seeds in jobs:
        for seed in seeds:
            alone = tmp_path / f"alone-s{seed}"
            cfg = tmp_path / f"alone-s{seed}.yaml"
            raw = json.loads(json.dumps(FLEET_CONFIG))
            raw["general"]["seed"] = seed
            raw["general"]["data_directory"] = str(alone)
            cfg.write_text(yaml.safe_dump(raw))
            assert run_from_config(str(cfg)) == 0
            job = f"{tenant}.{name}-s{seed}"
            assert _trajectory_stats(
                spool / "jobs" / job / "sim-stats.json"
            ) == _trajectory_stats(alone / "sim-stats.json"), job
