"""The http example end-to-end: real HTTP server + two clients as managed
processes over the simulated TCP stack, resolved via simulated DNS
(reference: examples/http-server nginx+curl on the 1_gbit_switch graph,
mirrored by src/test/examples)."""

import json
import pathlib
import subprocess

import pytest

from shadow_tpu.runtime.cli_run import run_from_config

EX = pathlib.Path(__file__).parent.parent / "examples" / "http"


@pytest.fixture(scope="module")
def http_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("http")
    bins = {}
    for name in ("http_server", "http_client"):
        dst = out / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(EX / f"{name}.c")], check=True)
        bins[name] = str(dst)
    return bins


def test_http_example(tmp_path, http_bins):
    cfg = tmp_path / "shadow.yaml"
    cfg.write_text(
        f"""
general:
  stop_time: 10 s
  data_directory: {tmp_path / "data"}
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {http_bins["http_server"]}
        args: 80 6
  client:
    network_node_id: 0
    quantity: 2
    processes:
      - path: {http_bins["http_client"]}
        args: [server, "80", "3", "20"]
        start_time: 100 ms
"""
    )
    assert run_from_config(str(cfg)) == 0
    data = tmp_path / "data"
    srv_out = (data / "server" / "http_server.1000.stdout").read_text()
    assert "server done" in srv_out
    for host in ("client1", "client2"):
        out = (data / host / f"http_client.100{1 if host == 'client1' else 2}.stdout").read_text()
        assert out.count("fetch") == 3
        assert "client done" in out
    stats = json.loads((data / "sim-stats.json").read_text())
    assert stats["syscall_counts"]["accept"] >= 6
    assert stats["syscall_counts"]["getaddrinfo"] >= 2
