"""Fat-tree topology ladder (BASELINE: iperf-like TCP saturation on a
fat-tree). Smoke at k=4 on the device engine; the generator scales to the
10k-host rung by k."""

import subprocess
import sys
import pathlib

import jax.numpy as jnp

GEN = pathlib.Path(__file__).parent.parent / "examples" / "fattree" / "gen_fattree.py"


def test_fattree_bulk_tcp_smoke():
    gml = subprocess.run(
        [sys.executable, str(GEN), "4"], capture_output=True, text=True, check=True
    ).stdout
    from shadow_tpu.engine import EngineConfig, init_state
    from shadow_tpu.engine.round import bootstrap, check_capacity, run_rounds_scan
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models.bulk import BulkTcpModel
    from shadow_tpu.simtime import NS_PER_SEC

    graph = NetworkGraph.from_gml(gml)
    # k=4: 4 core + 4 pods x (2 agg + 2 edge) = 20 nodes; edges hold hosts
    assert graph.num_nodes == 20
    edge_nodes = [i for i in range(graph.num_nodes) if graph.bw_up_bits[i] > 0]
    assert len(edge_nodes) == 8
    num_hosts = 32
    host_node = [edge_nodes[i % len(edge_nodes)] for i in range(num_hosts)]
    tables = compute_routing(graph).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=512,
        outbox_capacity=128,
        runahead_ns=graph.min_latency_ns(),
        seed=7,
    )
    model = BulkTcpModel(num_hosts=num_hosts, num_pairs=num_hosts // 2, total_bytes=200_000)
    st = init_state(cfg, model.init())
    st = bootstrap(st, model, cfg)
    st = run_rounds_scan(st, jnp.asarray(NS_PER_SEC, jnp.int64), 400, model, tables, cfg)
    check_capacity(st)
    # every server host received the full stream, exactly once
    delivered = jnp.sum(st.model.tcp.delivered, axis=1)[num_hosts // 2 :]
    assert int(jnp.sum(delivered == 200_000)) == num_hosts // 2, delivered
    assert int(st.packets_unroutable.sum()) == 0
