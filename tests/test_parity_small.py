"""Small parity features: compressed GML topologies (reference
src/test/compressed-graph/) and the per-host CPU frequency-ratio delay
model (reference src/main/host/cpu.rs:8-50)."""

import bz2
import gzip
import lzma

import numpy as np

from shadow_tpu.config.options import load_config_str
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel
from tests.topo import two_node_graph

GML = """graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.01 ]
]"""


def test_compressed_gml_loads_identically(tmp_path):
    plain = NetworkGraph.from_gml(GML)
    for suffix, opener in ((".gz", gzip.open), (".xz", lzma.open), (".bz2", bz2.open)):
        p = tmp_path / f"g.gml{suffix}"
        with opener(p, "wb") as f:
            f.write(GML.encode())
        g = NetworkGraph.from_file(p)
        np.testing.assert_array_equal(g.lat_ns, plain.lat_ns)
        np.testing.assert_array_equal(g.rel, plain.rel)
    # plain files keep working through the same entry point
    p = tmp_path / "g.gml"
    p.write_text(GML)
    g = NetworkGraph.from_file(p)
    np.testing.assert_array_equal(g.lat_ns, plain.lat_ns)


def test_cpu_frequency_config_parses():
    cfg = load_config_str(
        """
general: { stop_time: 1 s }
hosts:
  slow:
    network_node_id: 0
    cpu_frequency: 1500000000
    processes: [ { path: /bin/true } ]
  fast:
    network_node_id: 0
    processes: [ { path: /bin/true } ]
"""
    )
    by_name = {h.name: h for h in cfg.hosts}
    assert by_name["slow"].cpu_frequency_hz == 1_500_000_000
    assert by_name["fast"].cpu_frequency_hz is None


def test_cpu_frequency_scales_syscall_charge(tmp_path):
    graph = two_node_graph(10, 0.0)
    tables = compute_routing(graph).with_hosts([0, 1])
    k = NetKernel(
        tables,
        host_names=["half", "native"],
        host_nodes=[0, 1],
        data_dir=tmp_path / "d",
        syscall_latency_ns=1_000,
        vdso_latency_ns=10,
        cpu_freq_hz=[1_500_000_000, 0],
        native_cpu_freq_hz=3_000_000_000,
    )
    half, native = k.hosts
    assert half.syscall_latency_ns == 2_000  # half the clock, double the charge
    assert half.vdso_latency_ns == 20
    assert native.syscall_latency_ns == 1_000
    assert native.vdso_latency_ns == 10
