"""Shared test topologies (used by the TCP and hostk suites)."""

from shadow_tpu.graph import NetworkGraph


def two_node_graph(latency_ms=10, loss=0.0) -> NetworkGraph:
    """Two graph nodes with 1 ms self-loops and one lossy inter-node edge."""
    return NetworkGraph.from_gml(
        "\n".join(
            [
                "graph [",
                "  directed 0",
                "  node [ id 0 ]",
                "  node [ id 1 ]",
                '  edge [ source 0 target 0 latency "1 ms" ]',
                '  edge [ source 1 target 1 latency "1 ms" ]',
                f'  edge [ source 0 target 1 latency "{latency_ms} ms" packet_loss {loss} ]',
                "]",
            ]
        )
    )
