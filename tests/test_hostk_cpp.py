"""C++ runtime guest (reference: src/test/cpp): libstdc++ threads,
condition variables, chrono, iostreams, and TCP through the shim."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def cpp_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "cpp_guest"
    subprocess.run(
        ["c++", "-O2", "-std=c++17", "-pthread", "-o", str(out), str(GUESTS / "cpp_guest.cc")],
        check=True,
    )
    return str(out)


def test_cpp_guest_native(tmp_path, cpp_bin):
    """Paired-test contract: threads/condvars/TCP pass on the real
    kernel (the chrono-epoch check is sim-gated inside the guest)."""
    r = subprocess.run([cpp_bin], capture_output=True, text=True, cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "cpp all ok" in r.stdout
    assert "ok thread-condvar" in r.stdout


def test_cpp_guest_under_shim(tmp_path, cpp_bin):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / "d")
    p = k.add_process(ProcessSpec(host="box", args=[cpp_bin]))
    try:
        k.run(10 * NS_PER_SEC)
    finally:
        k.shutdown()
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "cpp all ok sum=15" in out
