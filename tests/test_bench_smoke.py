"""Tier-1 bench-harness smoke (the r05 null-regression guard): the
forced-CPU tiny rung must publish a NON-NULL metric, with every rung
running under the compile-budget autotuner (runtime/autotune.py) so no
rounds_per_chunk choice can time the child out — the published
`compile_probe` line must show the requested rpc corrected down when
its projected compile wall does not fit the budget.

This is the one deliberately-heavy test in the quick tier (~1 min, one
XLA compile of the tgen world on CPU): BENCH_r04/r05 both shipped with
the metric one config knob away from null, and the only thing that
actually pins "the bench cannot publish null" is running the real
harness end to end. Every optional section (native baseline, scaling
table, ensemble/sweep trials) is disabled via its env switch, and the
autotuner's probe cache is pre-seeded with an inflated probe wall — the
planner then corrects the rpc from the cache without paying the probe's
own scan compile (tier-1 budget; the live-probe path runs in the CLI
and the full-scale bench, and if the cache key ever drifts this test
still passes, just paying the probe again)."""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


def _seed_probe_cache(path) -> None:
    """Write a probe-wall entry for the exact world the CPU rung builds
    (bench._build_world(64)), inflated so any rpc > the floor projects
    past the budget — the r05 misconfiguration, injected via the cache."""
    from bench import _build_world
    from shadow_tpu.runtime.autotune import PROBE_RPC, _cache_key

    cfg, _, _ = _build_world(64)
    key = _cache_key(cfg, PROBE_RPC, "cpu")
    path.write_text(json.dumps({key: {"probe_wall_s": 600.0}}))


def test_bench_cpu_rung_publishes_non_null(tmp_path):
    cache = tmp_path / "autotune.json"
    _seed_probe_cache(cache)
    env = dict(
        os.environ,
        SHADOW_TPU_FORCE_CPU="1",
        SHADOW_TPU_BENCH_HOSTS="64",
        SHADOW_TPU_BENCH_CPU_HOSTS="64",
        SHADOW_TPU_BENCH_CPU_SIMSEC="0.02",
        SHADOW_TPU_BENCH_NATIVE="0",
        SHADOW_TPU_BENCH_SCALING="",
        SHADOW_TPU_BENCH_ENSEMBLE="0",
        SHADOW_TPU_BENCH_SWEEP="0",
        SHADOW_TPU_BENCH_OVERLAY="0",
        SHADOW_TPU_BENCH_MESH="0",
        SHADOW_TPU_BENCH_ELASTIC="0",
        SHADOW_TPU_AUTOTUNE_CACHE=str(cache),
    )
    r = subprocess.run(
        [sys.executable, BENCH],
        env=env, capture_output=True, text=True, timeout=700,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    last = json.loads(r.stdout.strip().splitlines()[-1])

    # the whole point: the harness publishes a number, never null
    assert last["value"] is not None and last["value"] > 0, last
    assert last["unit"] == "sim_s/wall_s"

    detail = last["detail"]
    main = detail["main"]
    assert main["events"] > 0

    # every rung ran under the autotuner, and the decision is published
    at = main["autotune"]
    assert at["source"] in ("probe", "cache", "floor")
    assert at["rounds_per_chunk"] <= at["requested"]

    # the attempt log carries the compile_probe line: a requested rpc
    # whose projected compile blows the budget is corrected DOWN before
    # the main compile (the r05 failure mode, inverted)
    probe = detail["attempts"][0]["compile_probe"]
    assert probe["chosen_rpc"] <= probe["requested_rpc"]
    assert main["rounds_per_chunk"] == at["rounds_per_chunk"]

    # adaptivity lanes are published per trial (window widths, live-lane
    # occupancy) so a regression in adaptivity is visible in BENCH_r*
    ad = main["adaptivity"]
    assert ad["iters"] > 0 and ad["lanes_live"] > 0
    assert 0 < ad["occupancy"] <= 1
    assert ad["window_ns_mean"] > 0
    assert ad["rounds"]["live"] > 0
