"""The fused Pallas round megakernel (engine/megakernel.py) is a pure
scheduling change: engine="megakernel" must produce BIT-IDENTICAL state to
the plain engine AND to the XLA pump — same queue contents, TCP fields,
relay/AQM state, RNG counters, sequence counters, byte/stream counters —
because its kernel body executes the exact same pump_microstep function,
just fused into one launch over VMEM-resident tiles. On CPU the kernel
runs in Pallas interpret mode (discharged to ordinary XLA ops), which is
the always-on conformance path these tests pin down.

Quick tier: one-launch smoke (megakernel_stage vs pump_stage on the same
state, leaf-for-leaf equal) — the kernel path can never silently rot on
CPU-only boxes. Slow tier: full-run digests vs the plain engine on the
tgen worlds of test_pump.py (shaped, lossy, unshaped), exact equality vs
the pump including iteration counts and under host tiling (grid > 1),
and the phold fallback contract (models without a pump_spec take the
plain handler inside the megakernel engine, bit-identically).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from test_pump import _assert_states_equal, _run, _world

from shadow_tpu.simtime import NS_PER_MS


def _assert_leaves_exact(a, b):
    """Stricter than _assert_states_equal: NO normalization — slot
    placement, iters_done, everything must match leaf-for-leaf."""
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        assert jnp.array_equal(la, lb), f"mismatch at {jax.tree_util.keystr(path)}"


def test_megakernel_one_launch_smoke():
    """Tier-1-safe: construct and run ONE fused launch in interpret mode
    (no TPU) against one XLA pump stage on the same state — leaf-exact."""
    from shadow_tpu.engine.megakernel import megakernel_stage
    from shadow_tpu.engine.pump import pump_stage

    cfg0, model, tables, st0 = _world(8, 0.0, 20_000_000, seed=3)
    cfg = dataclasses.replace(cfg0, pump_k=3)
    we = jnp.asarray(10 * NS_PER_MS, jnp.int64)
    a, rej_a = jax.jit(
        lambda s: pump_stage(s, we, model, tables, cfg)
    )(st0)
    b, rej_b = jax.jit(
        lambda s: megakernel_stage(s, we, model, tables, cfg)
    )(st0)
    # the bootstrap queue holds local stream-start events: not a pump
    # class, so both stages must reject (and mutate nothing else)
    assert bool(rej_a) and bool(rej_b)
    _assert_leaves_exact(a, b)


@pytest.mark.slow
@pytest.mark.parametrize("loss,bw", [(0.0, 20_000_000), (0.02, 20_000_000)])
def test_megakernel_bit_identical_tgen(loss, bw):
    """The engine-parametrized run of the pump equivalence worlds: full
    tgen runs under shaping/loss, digests equal to the plain engine."""
    cfg0, model, tables, st0 = _world(32, loss, bw)
    end = 120 * NS_PER_MS
    ref = _run(cfg0, model, tables, st0, end)
    got = _run(
        dataclasses.replace(cfg0, pump_k=6, engine="megakernel"),
        model, tables, st0, end,
    )
    assert int(ref.model.streams_done.sum()) > 0  # real traffic flowed
    # fused iterations must be fewer (the whole point) ...
    assert int(got.iters_done.sum()) < int(ref.iters_done.sum())
    # ... with identical simulation results.
    _assert_states_equal(ref, got)


@pytest.mark.slow
def test_megakernel_unshaped_world_matches():
    """No netstack shaping: only P2/P3 apply; defers never occur."""
    cfg0, model, tables, st0 = _world(16, 0.0, 0)
    cfg0 = dataclasses.replace(cfg0, use_netstack=False)
    end = 80 * NS_PER_MS
    ref = _run(cfg0, model, tables, st0, end)
    got = _run(
        dataclasses.replace(cfg0, pump_k=5, engine="megakernel"),
        model, tables, st0, end,
    )
    assert int(ref.model.streams_done.sum()) > 0
    _assert_states_equal(ref, got)


@pytest.mark.slow
def test_megakernel_matches_pump_exactly_tiled():
    """Leaf-exact equality with the XLA pump — including iters_done (same
    iteration structure) and slot placement — with the host axis split
    over a grid of 2 Pallas programs (megakernel_tile=8 at 16 hosts):
    tiling must be invisible."""
    cfg0, model, tables, st0 = _world(16, 0.02, 20_000_000)
    end = 80 * NS_PER_MS
    cfgp = dataclasses.replace(cfg0, pump_k=5)
    p = _run(cfgp, model, tables, st0, end)
    m = _run(
        dataclasses.replace(cfgp, engine="megakernel", megakernel_tile=8),
        model, tables, st0, end,
    )
    _assert_leaves_exact(p, m)


@pytest.mark.slow
def test_megakernel_bit_identical_phold():
    """Models without a pump_spec fall back to the plain handler inside
    the megakernel engine — bit-identically (the documented deferral
    contract for non-hot event kinds)."""
    from shadow_tpu.engine import EngineConfig, init_state
    from shadow_tpu.engine.round import bootstrap, run_until
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models.phold import PholdModel

    g = NetworkGraph.from_gml(
        """graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "3 ms" ]
]"""
    )
    tables = compute_routing(g).with_hosts([i % 2 for i in range(8)])
    cfg = EngineConfig(
        num_hosts=8, runahead_ns=g.min_latency_ns(), queue_capacity=32
    )
    model = PholdModel(num_hosts=8)
    st = init_state(cfg, model.init())
    st = bootstrap(st, model, cfg)
    a = run_until(st, 200 * NS_PER_MS, model, tables, cfg)
    b = run_until(
        st, 200 * NS_PER_MS, model, tables,
        dataclasses.replace(cfg, engine="megakernel"),
    )
    _assert_leaves_exact(a, b)
