"""Active-set compaction (engine/round.py handle_one_iteration_compact):
per-iteration gather of only the hosts with an eligible event must be
bit-identical to the full-width iteration — hosts are independent inside a
conservative window, so subset scheduling cannot reorder any host's event
sequence (the compaction analogue of the reference's work-stealing
scheduler being order-free within a round, thread_per_core.rs:188-206)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, run_until
from shadow_tpu.engine.sharded import ShardedRunner
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.phold import PholdModel
from shadow_tpu.models.tgen import TgenModel
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS


def _lossy_graph(n_nodes=8, seed=7):
    rng_py = random.Random(seed)
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "2 ms" ]')
    for i in range(n_nodes):
        for j in rng_py.sample(range(n_nodes), 3) + [(i + 1) % n_nodes]:
            if j != i:
                lat = rng_py.randrange(2, 12)
                lines.append(
                    f'  edge [ source {i} target {j} latency "{lat} ms" packet_loss 0.01 ]'
                )
    lines.append("]")
    return NetworkGraph.from_gml("\n".join(lines))


def _build_tgen(num_hosts, active_lanes, shaped=True):
    graph = _lossy_graph()
    host_node = [i % 8 for i in range(num_hosts)]
    tables = compute_routing(graph, block=16).with_hosts(host_node)
    clients = num_hosts // 2
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=128,
        outbox_capacity=16,
        runahead_ns=graph.min_latency_ns(),
        seed=5,
        use_netstack=shaped,
        max_iters_per_round=100_000,
        active_lanes=active_lanes,
    )
    model = TgenModel(
        num_hosts=num_hosts,
        num_clients=clients,
        num_servers=num_hosts - clients,
        resp_bytes=30_000,
        pause_ns=50 * NS_PER_MS,
    )
    bw = bw_bits_per_sec_to_refill(100_000_000) if shaped else None
    st = init_state(cfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw)
    return cfg, model, tables, bootstrap(st, model, cfg)


def _assert_states_equal(a, b):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree.flatten(b)[0]
    assert len(fa) == len(fb)
    for (path, x), y in zip(fa, fb):
        name = jax.tree_util.keystr(path)
        if "iters_done" in name or "lanes_live" in name:
            continue  # diagnostics: compaction legitimately splits waves
        if jnp.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=name)


@pytest.mark.parametrize("lanes", [8])
def test_tgen_compact_bit_identical(lanes):
    """lanes=8 forces heavy splitting (64 hosts, ~32 clients active at
    bootstrap, so most iterations handle a strict subset)."""
    end = 150_000_000
    cfg0, model, tables, st0 = _build_tgen(64, 0)
    ref = run_until(st0, end, model, tables, cfg0, rounds_per_chunk=32)
    cfgc, model, tables, st0c = _build_tgen(64, lanes)
    got = run_until(st0c, end, model, tables, cfgc, rounds_per_chunk=32)
    assert int(np.asarray(ref.events_handled).sum()) > 0
    _assert_states_equal(ref, got)


def test_phold_compact_bit_identical():
    num_hosts = 32
    graph = _lossy_graph()
    tables = compute_routing(graph, block=16).with_hosts([i % 8 for i in range(num_hosts)])

    def run(lanes):
        cfg = EngineConfig(
            num_hosts=num_hosts,
            queue_capacity=64,
            runahead_ns=graph.min_latency_ns(),
            seed=3,
            max_iters_per_round=100_000,
            active_lanes=lanes,
        )
        model = PholdModel(num_hosts=num_hosts)
        st = bootstrap(init_state(cfg, model.init()), model, cfg)
        return run_until(st, 300_000_000, model, tables, cfg, rounds_per_chunk=32)

    ref, got = run(0), run(6)
    assert int(np.asarray(ref.events_handled).sum()) > 0
    _assert_states_equal(ref, got)


def test_sharded_compact_matches_single_device():
    """Compaction under shard_map (per-shard active sets) must still match
    the unsharded full-width run."""
    num_hosts = 64
    end = 150_000_000
    cfg0, model, tables, st0 = _build_tgen(num_hosts, 0)
    ref = run_until(st0, end, model, tables, cfg0, rounds_per_chunk=16)

    cfgc, model, tables, stc = _build_tgen(num_hosts, 4)
    mesh = jax.make_mesh((jax.device_count(),), ("hosts",))
    runner = ShardedRunner(mesh, model, tables, cfgc, rounds_per_chunk=16)
    got = runner.run_until(stc, end)
    for name in ("events_handled", "packets_sent", "packets_dropped"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, name)), np.asarray(getattr(got, name)), err_msg=name
        )
    for name in ("streams_done", "bytes_down", "resets"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref.model, name)),
            np.asarray(getattr(got.model, name)),
            err_msg=name,
        )
