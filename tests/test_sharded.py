"""Multi-chip conformance: the sharded engine (hosts block-sharded over an
8-virtual-device mesh, exchange via all_gather over the mesh axis) must
produce bit-identical results to the single-device engine."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shadow_tpu import equeue
from shadow_tpu.engine import EngineConfig, ShardedRunner, init_state
from shadow_tpu.engine.round import bootstrap, run_until
from shadow_tpu.engine.sharded import AXIS
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models import PholdModel
from shadow_tpu.simtime import NS_PER_MS


def _setup(num_hosts, n_nodes=4, loss=0.1, seed=31):
    rng_py = random.Random(seed)
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "700 us" ]')
    for i in range(n_nodes):
        for j in range(i + 1, n_nodes):
            lines.append(
                f'  edge [ source {i} target {j} latency "{rng_py.randrange(2, 9)} ms" packet_loss {loss} ]'
            )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    host_node = [i % n_nodes for i in range(num_hosts)]
    tables = compute_routing(graph, block=8).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=32,
        outbox_capacity=8,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
    )
    model = PholdModel(num_hosts=num_hosts, min_delay_ns=1 * NS_PER_MS, max_delay_ns=6 * NS_PER_MS)
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    return cfg, model, tables, st


def test_sharded_matches_single_device():
    assert jax.device_count() == 8
    cfg, model, tables, st0 = _setup(num_hosts=16)
    end = 50 * NS_PER_MS

    st_single = run_until(st0, end, model, tables, cfg, rounds_per_chunk=16)

    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=16)
    st_sharded = runner.run_until(st0, end)

    for name in ["seq", "rng_counter", "packets_sent", "packets_dropped", "events_handled"]:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_single, name)), np.asarray(getattr(st_sharded, name)), err_msg=name
        )
    np.testing.assert_array_equal(
        np.asarray(st_single.model.recv_count), np.asarray(st_sharded.model.recv_count)
    )
    np.testing.assert_array_equal(
        np.asarray(st_single.model.send_count), np.asarray(st_sharded.model.send_count)
    )
    # queue contents identical per host (canonical order)
    for h in range(cfg.num_hosts):
        assert equeue.debug_sorted_events(st_sharded.queue, h) == equeue.debug_sorted_events(
            st_single.queue, h
        ), f"host {h}"
    assert int(st_sharded.queue.overflow.sum()) == 0
    assert int(st_sharded.outbox.overflow.sum()) == 0


def _setup_bulk(num_hosts, seed=17, exchange="all_to_all"):
    """Bulk-TCP world (handshake/Reno/retransmits + shaping) for the
    scaled sharded-equality check (the exchange seam that matters at 10k
    hosts, reference worker.rs:619-629)."""
    from shadow_tpu.models.bulk import BulkTcpModel
    from shadow_tpu.netstack import bw_bits_per_sec_to_refill

    rng_py = random.Random(seed)
    n_nodes = 8
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "1 ms" ]')
    for i in range(n_nodes):
        for j in range(i + 1, n_nodes):
            lines.append(
                f'  edge [ source {i} target {j} latency "{rng_py.randrange(2, 7)} ms" packet_loss 0.01 ]'
            )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    host_node = [i % n_nodes for i in range(num_hosts)]
    tables = compute_routing(graph, block=8).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=128,
        outbox_capacity=32,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
        use_netstack=True,
        exchange=exchange,
    )
    model = BulkTcpModel(
        num_hosts=num_hosts, num_pairs=num_hosts // 4, total_bytes=40_000
    )
    bw = bw_bits_per_sec_to_refill(50_000_000)
    st = bootstrap(
        init_state(cfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw),
        model,
        cfg,
    )
    return cfg, model, tables, st


@pytest.mark.parametrize("exchange", ["all_to_all", "all_gather", "segment"])
def test_sharded_bulk_tcp_1k_hosts_matches_single(exchange):
    """1024-host bulk-TCP (full simulated stack) sharded over 8 devices
    with the destination-bucketed all-to-all exchange — or the sort-based
    segment exchange's ppermute ring — must equal the single-device run
    bit for bit."""
    assert jax.device_count() == 8
    cfg, model, tables, st0 = _setup_bulk(num_hosts=1024, exchange=exchange)
    end = 40 * NS_PER_MS

    st_single = run_until(st0, end, model, tables, cfg, rounds_per_chunk=8)

    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=8)
    st_sharded = runner.run_until(st0, end)

    for name in ["seq", "rng_counter", "packets_sent", "packets_dropped", "events_handled"]:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_single, name)),
            np.asarray(getattr(st_sharded, name)),
            err_msg=name,
        )
    np.testing.assert_array_equal(
        np.asarray(st_single.model.tcp.delivered), np.asarray(st_sharded.model.tcp.delivered)
    )
    np.testing.assert_array_equal(
        np.asarray(st_single.model.conns_established),
        np.asarray(st_sharded.model.conns_established),
    )
    assert int(np.asarray(st_sharded.model.tcp.delivered).sum()) > 0
    assert int(st_sharded.queue.overflow.sum()) == 0
    assert int(st_sharded.outbox.overflow.sum()) == 0


def test_sharded_rejects_uneven_split():
    cfg, model, tables, st0 = _setup(num_hosts=12)  # 12 % 8 != 0
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    with pytest.raises(ValueError):
        ShardedRunner(mesh, model, tables, cfg)


def test_runahead_validation():
    cfg, model, tables, st0 = _setup(num_hosts=16)
    bad = EngineConfig(
        num_hosts=16, runahead_ns=10**12, seed=1, queue_capacity=32, outbox_capacity=8
    )
    with pytest.raises(ValueError):
        run_until(st0, 10 * NS_PER_MS, model, tables, bad)
