"""fork/waitpid tests: guests spawning managed child processes
(reference: Process::spawn/fork process.rs, the clone/fork handlers in
syscall/handler/clone.rs, src/test/clone + examples with multi-process
guests)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def fork_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "fork_guest"
    subprocess.run(["cc", "-O2", "-o", str(out), str(GUESTS / "fork_guest.c")], check=True)
    return str(out)


def _run(tmp_path, fork_bin, sub="a"):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / sub)
    p = k.add_process(ProcessSpec(host="box", args=[fork_bin]))
    try:
        k.run(5 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, p


def test_fork_guest_native(tmp_path, fork_bin):
    r = subprocess.run([fork_bin], capture_output=True, text=True, cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "fork all ok" in r.stdout


def test_fork_guest_under_shim(tmp_path, fork_bin):
    k, p = _run(tmp_path, fork_bin)
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "fork all ok" in out
    assert k.syscall_counts["fork"] == 2
    assert k.syscall_counts["wait4"] >= 3
    # the children ran as managed processes with their own vpids
    assert len(k.procs) == 3
    assert all(pr.state == "exited" for pr in k.procs)


def test_fork_deterministic(tmp_path, fork_bin):
    a = _run(tmp_path, fork_bin, "r1")[1].stdout()
    b = _run(tmp_path, fork_bin, "r2")[1].stdout()
    assert a == b
