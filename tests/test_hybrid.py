"""Hybrid-scheduler conformance: managed guests on the CPU kernel with
their packets on the device engine must reproduce the serial kernel's
transfers, guest-visible timelines, and logs bit-for-bit (the round-2
coupling milestone; reference: manager.rs:392-478, worker.rs:399-402).

Both sides run with the same round-window delivery clamp (window_ns =
engine runahead), the same threefry streams, and the same int64 token-
bucket/CoDel closed forms — so everything observable must match exactly:
guest stdout (including guest-visible timestamps), strace syscall
sequences, the packet event log (compared as a time-sorted multiset;
drain batching changes append order, never content), and final stats.
"""

import pathlib
import subprocess

import numpy as np
import pytest

from shadow_tpu.engine import EngineConfig
from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.runtime.hybrid import HybridScheduler
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"
W = 1 * NS_PER_MS  # two_node_graph's min link latency (the self-loops)


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    built = {}
    for name in ("tcp_echo_server", "tcp_client", "udp_blast"):
        dst = out / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True)
        built[name] = str(dst)
    return built


def _build(tmp_path, sub, hybrid, loss=0.0, seed=1, bw_up=(0, 0), bw_down=(0, 0)):
    graph = two_node_graph(10, loss)
    tables = compute_routing(graph).with_hosts([0, 1])
    k = NetKernel(
        tables,
        host_names=["server", "client"],
        host_nodes=[0, 1],
        seed=seed,
        data_dir=tmp_path / sub,
        window_ns=W,
        bw_up_bits=list(bw_up),
        bw_down_bits=list(bw_down),
    )
    runner = None
    if hybrid:
        use_net = any(bw_up) or any(bw_down)
        ecfg = EngineConfig(
            num_hosts=2,
            queue_capacity=256,
            outbox_capacity=64,
            runahead_ns=W,
            seed=seed,
            use_netstack=use_net,
        )
        runner = HybridScheduler(
            k,
            tables,
            ecfg,
            tx_bytes_per_interval=(
                np.asarray(bw_bits_per_sec_to_refill(np.array(bw_up, dtype=np.int64)))
                if use_net
                else None
            ),
            rx_bytes_per_interval=(
                np.asarray(bw_bits_per_sec_to_refill(np.array(bw_down, dtype=np.int64)))
                if use_net
                else None
            ),
        )
    return k, runner


def _run_tcp(tmp_path, bins, sub, hybrid, nbytes=50_000, loss=0.0, seed=1, until_s=60):
    k, runner = _build(tmp_path, sub, hybrid, loss=loss, seed=seed)
    srv = k.add_process(ProcessSpec(host="server", args=[bins["tcp_echo_server"], "8080", "1"]))
    cli = k.add_process(
        ProcessSpec(
            host="client",
            args=[bins["tcp_client"], "server", "8080", str(nbytes)],
            start_ns=100 * NS_PER_MS,
        )
    )
    try:
        (runner.run if runner else k.run)(until_s * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, runner, srv, cli


def _assert_equal_worlds(a, b):
    """a, b: (kernel, runner, server proc, client proc) from the two modes."""
    ka, _, sa, ca = a
    kb, _, sb, cb = b
    assert ca.stdout() == cb.stdout()  # guest-visible bytes AND timestamps
    assert sa.stdout() == sb.stdout()
    assert ca.exit_code == cb.exit_code
    assert [s for _, s, _ in ca.syscall_log] == [s for _, s, _ in cb.syscall_log]
    assert [s for _, s, _ in sa.syscall_log] == [s for _, s, _ in sb.syscall_log]
    assert sorted(ka.event_log) == sorted(kb.event_log)
    assert ka.stats() == kb.stats()


def test_hybrid_matches_serial_tcp(tmp_path, bins):
    a = _run_tcp(tmp_path, bins, "serial", hybrid=False)
    b = _run_tcp(tmp_path, bins, "hybrid", hybrid=True)
    assert b[1].device_passes > 0  # the device engine actually carried traffic
    assert "echoed 50000/50000 bytes" in b[3].stdout().decode()
    _assert_equal_worlds(a, b)


def test_hybrid_matches_serial_tcp_under_loss(tmp_path, bins):
    a = _run_tcp(tmp_path, bins, "serial_l", hybrid=False, loss=0.03, until_s=120)
    b = _run_tcp(tmp_path, bins, "hybrid_l", hybrid=True, loss=0.03, until_s=120)
    assert sum(h.packets_dropped for h in b[0].hosts) > 0  # loss happened on device
    _assert_equal_worlds(a, b)


def test_hybrid_run_twice_deterministic(tmp_path, bins):
    a = _run_tcp(tmp_path, bins, "h1", hybrid=True, loss=0.02)
    b = _run_tcp(tmp_path, bins, "h2", hybrid=True, loss=0.02)
    assert a[3].stdout() == b[3].stdout()
    assert a[0].event_log == b[0].event_log
    assert a[0].stats() == b[0].stats()


def _run_blast(tmp_path, bins, sub, hybrid, bw_down, count=50, size=1200):
    k, runner = _build(
        tmp_path, sub, hybrid, bw_down=(bw_down, 0), seed=3
    )
    snk = k.add_process(ProcessSpec(host="server", args=[bins["udp_blast"], "sink", "7000", str(count)]))
    k.add_process(
        ProcessSpec(
            host="client",
            args=[bins["udp_blast"], "send", "11.0.0.1", "7000", str(count), str(size)],
            start_ns=100 * NS_PER_MS,
        )
    )
    try:
        (runner.run if runner else k.run)(30 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, runner, snk


def test_hybrid_matches_serial_shaped_udp(tmp_path, bins):
    """Receiver-side bandwidth + CoDel: the device ingress path (token
    bucket departures, AQM drops) must time and drop identically."""
    ka, _, snka = _run_blast(tmp_path, bins, "sblast", hybrid=False, bw_down=1_000_000)
    kb, runner, snkb = _run_blast(tmp_path, bins, "hblast", hybrid=True, bw_down=1_000_000)
    assert snka.stdout() == snkb.stdout()  # same datagrams, same arrival span
    assert sorted(ka.event_log) == sorted(kb.event_log)
    assert ka.stats() == kb.stats()
    assert sum(h.codel_dropped for h in kb.hosts) == sum(h.codel_dropped for h in ka.hosts)
