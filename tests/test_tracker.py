"""The tracker plane (engine/state.py TrackerState + utils/tracker.py).

Contracts pinned here:

  * counters are leaf-exact identical across the plain, pump, and
    megakernel engines (classification is by event kind / wire size /
    flow-table delta — properties of the event sequence, which the
    engines already reproduce bit-identically);
  * tracker ON vs OFF leaves the SimState trajectory leaf-exact
    unchanged (tracker leaves are write-only);
  * the pipelined driver stays leaf-exact vs the synchronous driver
    with the tracker enabled (the quiescent-extra-chunk path restores
    the round counters from the probe, like `now`);
  * heartbeat lines and sim-stats.json keep a golden shape on phold and
    tgen, and the per-host lines stay parseable by tools/parse_shadow.py;
  * the Chrome trace is valid JSON with well-nested spans;
  * `--tracker --trace-file` runs end-to-end from the CLI on CPU (the
    tier-1 tooling smoke) and the CapacityError names the saturated
    counter.
"""

import dataclasses
import io
import json
import pathlib

import jax
import jax.numpy as jnp
import pytest

from test_pipeline import _phold_world
from test_pump import _world as _tgen_world

from shadow_tpu.engine.round import (
    CapacityError,
    check_capacity,
    host_stats,
    run_until,
)
from shadow_tpu.simtime import NS_PER_MS
from shadow_tpu.utils.tracker import Tracker


def _assert_leaves_exact(a, b, skip=None):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        key = jax.tree_util.keystr(path)
        if skip and skip in key:
            continue
        assert jnp.array_equal(la, lb), f"mismatch at {key}"


TRACKER_LEAVES = (
    "ev_local", "ev_tcp", "bytes_ctrl", "bytes_data", "retrans_segs",
    "queue_hwm", "outbox_hwm", "rounds_live", "rounds_idle",
)


# --- cross-engine / on-off equivalence ----------------------------------


def test_tracker_counters_cross_engine_pump_tgen():
    """Tier-1 tentpole pin: with the tracker on, a full tgen run under
    shaping+loss is leaf-exact identical (including every TrackerState
    leaf) between the plain engine and the pump microscan."""
    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    end = 30 * NS_PER_MS
    plain = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, engine="plain", tracker=True),
        rounds_per_chunk=8,
    )
    pump = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, engine="pump", pump_k=3, tracker=True),
        rounds_per_chunk=8,
    )
    tr = plain.tracker
    # the world actually exercised the counters
    assert int(tr.ev_tcp.sum()) > 0 or int(tr.ev_local.sum()) > 0
    assert int(tr.bytes_data.sum()) > 0
    assert int(tr.queue_hwm.max()) > 0
    for name in TRACKER_LEAVES:
        assert jnp.array_equal(
            getattr(plain.tracker, name), getattr(pump.tracker, name)
        ), name


@pytest.mark.slow
def test_tracker_counters_cross_engine_megakernel_tgen():
    """Same pin against the fused Pallas megakernel (interpret mode on
    CPU): the kernel body runs the same pump_microstep, so the tracker
    lanes in its carry must come back leaf-exact."""
    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    end = 30 * NS_PER_MS
    plain = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, engine="plain", tracker=True),
        rounds_per_chunk=8,
    )
    mega = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg0, engine="megakernel", pump_k=3, tracker=True),
        rounds_per_chunk=8,
    )
    for name in TRACKER_LEAVES:
        assert jnp.array_equal(
            getattr(plain.tracker, name), getattr(mega.tracker, name)
        ), name


def test_tracker_on_off_trajectory_unchanged_phold():
    """cfg.tracker must be write-only observability: every non-tracker
    leaf of the final state is identical with the plane on or off (and
    off leaves the tracker leaves at zero — it costs nothing)."""
    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    off = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    on = run_until(
        st0, end, model, tables,
        dataclasses.replace(cfg, tracker=True),
        rounds_per_chunk=4,
    )
    _assert_leaves_exact(off, on, skip=".tracker")
    for name in TRACKER_LEAVES:
        assert int(jnp.sum(getattr(off.tracker, name))) == 0, name
    assert int(on.tracker.rounds_live) > 0
    assert int(jnp.sum(on.tracker.ev_local)) > 0


def test_tracker_pipelined_matches_sync():
    """The depth-2 pipeline stays leaf-exact with the tracker enabled:
    the quiescent extra chunk's idle-round counts are restored from the
    probe exactly like `now`."""
    cfg0, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg0, tracker=True)
    end = 40 * NS_PER_MS
    sync = run_until(
        st0, end, model, tables, cfg, rounds_per_chunk=4, pipeline=False
    )
    piped = run_until(
        st0, end, model, tables, cfg, rounds_per_chunk=4, pipeline=True
    )
    _assert_leaves_exact(sync, piped)


@pytest.mark.slow
def test_tracker_sharded_matches_single_device():
    """Sharded over the 8-virtual-device mesh, the tracker leaves come
    back identical to the single-device run (probe lanes psum/pmax over
    the mesh; per-host rows exchange-invariant)."""
    import numpy as np
    from jax.sharding import Mesh

    from test_sharded import _setup

    from shadow_tpu.engine import ShardedRunner
    from shadow_tpu.engine.sharded import AXIS

    cfg0, model, tables, st0 = _setup(num_hosts=16)
    cfg = dataclasses.replace(cfg0, tracker=True)
    end = 50 * NS_PER_MS
    single = run_until(st0, end, model, tables, cfg, rounds_per_chunk=16)
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    runner = ShardedRunner(mesh, model, tables, cfg, rounds_per_chunk=16)
    sharded = runner.run_until(st0, end)
    for name in TRACKER_LEAVES:
        np.testing.assert_array_equal(
            np.asarray(getattr(single.tracker, name)),
            np.asarray(getattr(sharded.tracker, name)),
            err_msg=name,
        )


# --- probe / heartbeat / stats shapes -----------------------------------


def test_probe_tracker_lanes_consistent():
    """The widened probe's tracker lanes agree with the final state's
    counters, and ev_packet derives correctly."""
    cfg0, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg0, tracker=True)
    probes = []
    st = run_until(
        st0, 20 * NS_PER_MS, model, tables, cfg,
        rounds_per_chunk=4, on_chunk=probes.append,
    )
    p = probes[-1]
    assert p.events_handled == int(st.events_handled.sum())
    assert p.ev_local == int(st.tracker.ev_local.sum())
    assert p.ev_tcp == int(st.tracker.ev_tcp.sum())
    assert p.ev_packet == p.events_handled - p.ev_local - p.ev_tcp
    assert p.drop_loss == int(st.packets_dropped.sum())
    assert p.queue_hwm == int(st.tracker.queue_hwm.max())
    assert p.outbox_hwm == int(st.tracker.outbox_hwm.max())
    assert p.rounds_live == int(st.tracker.rounds_live)
    assert p.rounds_live > 0
    assert p.queue_overflow == 0 and p.outbox_overflow == 0


def test_ensemble_flatten_pairs_window_numerator_and_denominator():
    """mean_ns = win_ns_sum / live must take BOTH terms from the same
    population: the ensemble flatten sums win_ns_sum across replicas and
    ships the summed live-round denominator as win_rounds_live — maxing
    each independently would divide replica A's width sum by replica B's
    round count and publish a mean no replica actually had."""
    import numpy as np

    from shadow_tpu.runtime.ensemble import flatten_host_stats

    hs = {
        "rounds_live": np.array([10, 20]),
        "rounds_idle": np.array([1, 2]),
        "win_ns_sum": np.array([100_000_000, 60_000_000]),
        "lanes_live": np.ones((2, 3), np.int64),
    }
    out = flatten_host_stats(hs)
    assert out["win_ns_sum"] == 160_000_000
    assert out["win_rounds_live"] == 30  # -> weighted mean ~5.33e6, exact
    assert out["rounds_live"] == 20  # the rounds block keeps its max
    assert out["lanes_live"].shape == (6,)


def test_window_occupancy_scales_by_iteration_planes():
    """The occupancy denominator must shrink by the iteration-plane
    count: iters_done sums PER-PLANE drain-loop counts (one per shard's
    row 0, or per replica after the ensemble flatten) while each such
    iteration scans only H/planes lanes — without the correction a
    sharded fold under-reports occupancy by exactly the shard factor."""
    cfg0, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg0, tracker=True)
    names = [f"h{i}" for i in range(cfg.num_hosts)]
    st = run_until(st0, 40 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4)
    tr1 = Tracker(host_names=names)
    tr1.finalize(host_stats(st))
    occ1 = tr1.stats_dict()["window"]["occupancy"]
    # a plane count that divides H, like the scheduler enforces for shards
    planes = 2
    assert cfg.num_hosts % planes == 0
    tr2 = Tracker(host_names=names)
    tr2.num_shards = planes
    tr2.finalize(host_stats(st))
    occ2 = tr2.stats_dict()["window"]["occupancy"]
    assert occ1 > 0
    assert occ2 == pytest.approx(occ1 * planes, rel=0.05)


def test_heartbeat_lines_and_stats_fold_phold():
    """Driving with a Tracker attached renders per-host heartbeat lines
    in the format tools/parse_shadow.py parses, and the end-of-run fold
    has the golden sim-stats shape."""
    import re
    import sys

    from shadow_tpu.utils import shadow_log

    cfg0, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg0, tracker=True)
    names = [f"h{i}" for i in range(cfg.num_hosts)]
    tracker = Tracker(host_names=names, heartbeat_ns=10 * NS_PER_MS)
    sink = io.StringIO()
    shadow_log.set_sink(sink)
    try:
        st = run_until(
            st0, 40 * NS_PER_MS, model, tables, cfg,
            rounds_per_chunk=4, tracker=tracker,
        )
    finally:
        shadow_log.flush()
        shadow_log.set_sink(None)
    out = sink.getvalue()
    lines = [ln for ln in out.splitlines() if "tracker: " in ln]
    assert lines, out
    # the leading fields stay parse_shadow-compatible
    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))
    try:
        from parse_shadow import TRACKER

        assert TRACKER.search(lines[0]), lines[0]
    finally:
        sys.path.pop(0)
    pat = re.compile(
        r"tracker: bytes_sent=\d+ bytes_recv=\d+ packets_sent=\d+ "
        r"packets_dropped=\d+ events=\d+ ev_local=\d+ ev_tcp=\d+ "
        r"ev_packet=\d+ drop_codel=\d+ drop_unroutable=\d+ bytes_ctrl=\d+ "
        r"bytes_data=\d+ retrans=\d+ queue_hwm=\d+ outbox_hwm=\d+"
    )
    for ln in lines:
        assert pat.search(ln), ln

    tracker.finalize(host_stats(st))
    stats = tracker.stats_dict()
    assert set(stats["events_by_kind"]) == {"local", "tcp", "packet"}
    assert set(stats["drops"]) == {"loss", "codel", "unroutable"}
    assert set(stats["bytes"]) == {"ctrl", "data", "retrans_segments"}
    assert set(stats["high_water"]) == {"queue", "outbox"}
    assert set(stats["rounds"]) == {"live", "idle"}
    total = sum(stats["events_by_kind"].values())
    assert total == int(st.events_handled.sum())
    assert stats["rounds"]["live"] > 0
    assert "probe_fetch" in stats["phases"]
    assert stats["phases"]["probe_fetch"]["count"] >= 3


@pytest.mark.slow
def test_heartbeat_and_stats_fold_tgen():
    """The tgen golden-shape check: TCP traffic populates the byte
    classes and the tcp event kind; heartbeat lines render for every
    host."""
    from shadow_tpu.utils import shadow_log

    cfg0, model, tables, st0 = _tgen_world(8, 0.02, 20_000_000, seed=3)
    cfg = dataclasses.replace(cfg0, tracker=True)
    names = [f"host{i}" for i in range(cfg.num_hosts)]
    tracker = Tracker(host_names=names, heartbeat_ns=5 * NS_PER_MS)
    sink = io.StringIO()
    shadow_log.set_sink(sink)
    try:
        st = run_until(
            st0, 30 * NS_PER_MS, model, tables, cfg,
            rounds_per_chunk=4, tracker=tracker,
        )
    finally:
        shadow_log.flush()
        shadow_log.set_sink(None)
    lines = [ln for ln in sink.getvalue().splitlines() if "tracker: " in ln]
    assert len(lines) >= cfg.num_hosts
    tracker.finalize(host_stats(st))
    stats = tracker.stats_dict()
    assert stats["events_by_kind"]["tcp"] > 0
    assert stats["bytes"]["data"] > 0
    assert stats["bytes"]["ctrl"] > 0
    assert stats["high_water"]["queue"] > 0


# --- chrome trace -------------------------------------------------------


def test_chrome_trace_valid_and_well_nested(tmp_path):
    """A 3-chunk CPU run emits a Perfetto-loadable trace: valid JSON,
    every complete-span has numeric ts/dur, and spans on one thread are
    well-nested (disjoint or contained — never partially overlapping)."""
    cfg0, model, tables, st0 = _phold_world()
    cfg = dataclasses.replace(cfg0, tracker=True)
    path = tmp_path / "trace.json"
    tracker = Tracker(trace_path=str(path))
    probes = []
    run_until(
        st0, 20 * NS_PER_MS, model, tables, cfg,
        rounds_per_chunk=4, on_chunk=probes.append, tracker=tracker,
    )
    assert len(probes) >= 3  # at least 3 chunks dispatched
    assert tracker.write_trace() == str(path)
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"compile+launch", "chunk_launch", "probe_fetch", "donate_copy"} <= names
    for e in spans:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # well-nested per thread
    by_tid = {}
    for e in spans:
        by_tid.setdefault(e["tid"], []).append(e)
    eps = 1e-3  # float-us rounding slack
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        for i, a in enumerate(evs):
            for b in evs[i + 1 :]:
                a0, a1 = a["ts"], a["ts"] + a["dur"]
                b0, b1 = b["ts"], b["ts"] + b["dur"]
                disjoint = b0 >= a1 - eps
                contained = b1 <= a1 + eps
                assert disjoint or contained, (a, b)


# --- CLI / manager end-to-end (the tier-1 tooling smoke) ----------------


CLI_YAML = """
general:
  stop_time: "120 ms"
  seed: 5
  heartbeat_interval: "50 ms"
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 ]
        node [ id 1 ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 1 target 1 latency "1 ms" ]
        edge [ source 0 target 1 latency "5 ms" packet_loss 0.02 ]
      ]
experimental:
  queue_capacity: 32
hosts:
  node:
    network_node_id: 0
    quantity: 4
    processes:
      - path: phold
        args: {{ min_delay: "1 ms", max_delay: "8 ms" }}
"""


def test_cli_tracker_trace_end_to_end(tmp_path):
    """`shadow-tpu run --tracker --trace-file` on CPU produces a
    Perfetto-loadable trace and a sim-stats.json carrying per-kind event
    counts, drop reasons, and high-water marks."""
    from shadow_tpu.cli import main

    data = tmp_path / "data"
    conf = tmp_path / "c.yaml"
    conf.write_text(CLI_YAML.format(data_dir=data))
    trace = tmp_path / "trace.json"
    assert main(["run", str(conf), "--tracker", "--trace-file", str(trace)]) == 0
    stats = json.loads((data / "sim-stats.json").read_text())
    tr = stats["tracker"]
    assert sum(tr["events_by_kind"].values()) == stats["events_handled"]
    assert set(tr["drops"]) == {"loss", "codel", "unroutable"}
    assert tr["high_water"]["queue"] > 0
    assert tr["rounds"]["live"] > 0
    assert "compile+launch" in tr["phases"]
    doc = json.loads(trace.read_text())
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


# --- satellites ---------------------------------------------------------


def test_capacity_error_names_saturated_counter():
    """The capacity check names WHICH counter saturated (queue vs
    outbox) instead of only the total."""
    cfg, model, tables, st0 = _phold_world()
    bad = st0.replace(
        queue=st0.queue.replace(overflow=st0.queue.overflow.at[0].add(3))
    )
    with pytest.raises(CapacityError, match=r"queue\.overflow=3") as ei:
        check_capacity(bad)
    assert "saturated: queue" in str(ei.value)
    bad2 = st0.replace(
        outbox=st0.outbox.replace(overflow=st0.outbox.overflow.at[0].add(2))
    )
    with pytest.raises(CapacityError, match=r"outbox\.overflow=2") as ei:
        check_capacity(bad2)
    assert "saturated: outbox/exchange" in str(ei.value)
    # the chunk driver raises the same enriched error from the probe lanes
    with pytest.raises(CapacityError, match=r"queue\.overflow=3"):
        run_until(
            bad, 400 * NS_PER_MS, model, tables, cfg,
            rounds_per_chunk=4,
        )


def test_progress_line_renders_rates(capsys):
    """The status line shows sync-free events/sec and sim-sec/wall-sec
    once it has two probe samples."""
    from shadow_tpu.utils.progress import ProgressLine

    p = ProgressLine(enabled=True)
    p.update(100_000_000, 1_000_000_000, events=1000)
    p._last = 0.0  # bypass the 0.5 s render throttle
    p.update(300_000_000, 1_000_000_000, events=51_000)
    err = capsys.readouterr().err
    assert "ev/s" in err and "sim-s/s" in err
    p.finish(1_000_000_000)
