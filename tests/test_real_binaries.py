"""Real, unmodified distro binaries as managed processes: the reference's
identity is running stock software (curl, nginx, wget) in-sim unchanged
(reference: examples/http-server/shadow.yaml, src/test/examples/). These
tests run system /usr/bin/curl and /usr/bin/wget against a guest HTTP
server over the simulated network — resolver threads, simulated DNS,
sim-time clocks and all — and check run-twice determinism of the strace
output, the analogue of the reference determinism suite
(src/test/determinism/CMakeLists.txt:1-40)."""

import json
import os
import pathlib
import re
import subprocess

import pytest

from shadow_tpu.runtime.cli_run import run_from_config

EXAMPLES = pathlib.Path(__file__).parent.parent / "examples"

CURL = "/usr/bin/curl"
WGET = "/usr/bin/wget"

needs_curl = pytest.mark.skipif(not os.access(CURL, os.X_OK), reason="no system curl")
needs_wget = pytest.mark.skipif(not os.access(WGET, os.X_OK), reason="no system wget")


@pytest.fixture(scope="module")
def server_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "http_server"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(EXAMPLES / "http" / "http_server.c")], check=True
    )
    return str(out)


CONFIG = """
general:
  stop_time: 10 s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {server_bin}
        args: 80 {nreq}
  client:
    network_node_id: 0
    processes:
      - path: {client_bin}
        args: {client_args}
        start_time: 1 s
{extra}
"""


def _run(tmp_path, server_bin, client_bin, client_args, sub="a", nreq=1, extra=""):
    d = tmp_path / sub
    d.mkdir(parents=True)
    cfg = d / "shadow.yaml"
    cfg.write_text(
        CONFIG.format(
            data_dir=d / "data",
            server_bin=server_bin,
            nreq=nreq,
            client_bin=client_bin,
            client_args=json.dumps(client_args),
            extra=extra,
        )
    )
    rc = run_from_config(str(cfg))
    return rc, d / "data"


@needs_curl
def test_system_curl_fetches_in_sim(tmp_path, server_bin):
    rc, data = _run(
        tmp_path,
        server_bin,
        CURL,
        ["-sS", "--max-time", "5", "-o", "page.html", "http://server/"],
    )
    assert rc == 0
    page = (data / "client" / "page.html").read_bytes()
    assert b"The quick brown fox" in page
    stats = json.loads((data / "sim-stats.json").read_text())
    # the threaded resolver ran under the shim: clone + join + futexes
    assert stats["syscall_counts"].get("clone", 0) >= 1
    assert stats["syscall_counts"].get("getaddrinfo", 0) >= 1


@needs_wget
def test_system_wget_fetches_in_sim(tmp_path, server_bin):
    rc, data = _run(
        tmp_path,
        server_bin,
        WGET,
        ["-q", "-T", "5", "-O", "page.html", "http://server/"],
    )
    assert rc == 0
    page = (data / "client" / "page.html").read_bytes()
    assert b"The quick brown fox" in page


@needs_curl
def test_system_curl_run_twice_strace_identical(tmp_path, server_bin):
    """Deterministic-mode strace + fetched bytes must be byte-identical
    across runs — stock curl's entire observable execution (resolver
    thread scheduling, poll timing, TCP dynamics) replays exactly."""
    outs = []
    for sub in ("r1", "r2"):
        rc, data = _run(
            tmp_path,
            server_bin,
            CURL,
            ["-sS", "--max-time", "5", "-o", "page.html", "http://server/"],
            sub=sub,
            extra="experimental:\n  strace_logging_mode: deterministic\n",
        )
        assert rc == 0
        files = {}
        for p in sorted(data.rglob("*")):
            if p.suffix in (".strace", ".stdout") or p.name == "page.html":
                files[str(p.relative_to(data))] = p.read_bytes()
        assert any(n.endswith(".strace") for n in files), sorted(files)
        outs.append(files)
    assert outs[0].keys() == outs[1].keys()
    for name in outs[0]:
        assert outs[0][name] == outs[1][name], f"run-twice diff in {name}"


@needs_curl
def test_system_curl_sees_simulated_time(tmp_path, server_bin):
    """curl -w timing comes from the simulated clock: total time for a
    same-switch fetch is a few ms of sim time regardless of how long the
    serial kernel took on the wall."""
    rc, data = _run(
        tmp_path,
        server_bin,
        CURL,
        [
            "-sS",
            "--max-time",
            "5",
            "-o",
            "page.html",
            "-w",
            "dns=%{time_namelookup} total=%{time_total}\\n",
            "http://server/",
        ],
    )
    assert rc == 0
    out = (data / "client").glob("*.stdout")
    text = "".join(p.read_text() for p in out)
    m = re.search(r"total=([0-9.]+)", text)
    assert m, text
    # 1 ms links: handshake + request + response ≈ 4-computed on sim time;
    # anything under a second proves the clock is simulated, not wall
    assert 0.0 < float(m.group(1)) < 1.0, text
