"""Raw SYS_futex emulation: FUTEX_WAIT/WAKE across guest threads, glibc
semaphores (which issue raw futex, not interposed pthread symbols), WAIT
timeouts on simulated time, the serialized value-check fast path, and
fork-style raw clone routing (reference: src/main/host/futex.c,
futex_table.c, syscall/futex.c; clone birth managed_thread.rs:294-365)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def futex_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "futex_guest"
    subprocess.run(
        ["cc", "-O2", "-pthread", "-o", str(out), str(GUESTS / "futex_guest.c")],
        check=True,
    )
    return str(out)


def _run(tmp_path, futex_bin, sub="a", seed=1):
    tables = compute_routing(two_node_graph()).with_hosts([0, 1])
    k = NetKernel(
        tables,
        host_names=["h0", "h1"],
        host_nodes=[0, 1],
        seed=seed,
        data_dir=tmp_path / sub,
    )
    p = k.add_process(ProcessSpec(host="h0", args=[futex_bin]))
    try:
        k.run(30 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, p


def test_raw_futex_semantics(tmp_path, futex_bin):
    k, p = _run(tmp_path, futex_bin)
    assert p.exit_code == 0, p.stderr().decode() + p.stdout().decode()
    out = p.stdout().decode()
    lines = dict(
        (ln.split()[0], ln) for ln in out.splitlines() if ln.strip()
    )

    # 1. the waiter parked on the futex until the main thread's wake, which
    # happened after a 50ms simulated sleep — the wait itself took sim time
    assert "futex_wait ret=0 val=7" in lines["futex_wait"]
    waited = int(lines["futex_wait"].split("waited_ms=")[1])
    assert 45 <= waited <= 80, lines["futex_wait"]
    assert "woken=1" in out

    # 2. semaphore ping-pong completed all rounds
    assert "pings=5" in out

    # 3. WAIT timeout fired at ~30ms of *simulated* time
    assert "timeout ret=-1 errno_ok=1" in out
    t_ms = int(lines["timeout"].split("waited_ms=")[1])
    assert 28 <= t_ms <= 45, lines["timeout"]

    # 4. serialized value check: mismatch returns EAGAIN without an IPC trip
    assert "eagain ret=-1 errno_ok=1" in out

    # 5. raw fork-style clone became a managed child; its raw _exit(42)
    # status came back through the managed waitpid (duplicate earlier
    # lines in stdout are the inherited unflushed stdio buffer, exactly
    # as on real Linux when stdout is a file)
    assert "clone child pid=" in out
    assert "clone parent: child=1 status=42" in out

    # the raw futex calls went through the kernel's table
    assert k.syscall_counts.get("futex", 0) >= 2


def test_raw_futex_deterministic(tmp_path, futex_bin):
    a = _run(tmp_path, futex_bin, sub="d1")
    b = _run(tmp_path, futex_bin, sub="d2")
    assert a[1].stdout() == b[1].stdout()
    assert [s for _, s, _ in a[1].syscall_log] == [s for _, s, _ in b[1].syscall_log]
