"""Chaos matrix (docs/robustness.md "Chaos testing"): deterministic
fault injection against every seam the runtime claims to survive, and
the degradation ladder opposite it.

The contract under test: for every fault class x (run / resume / sweep)
path, the outcome is either a completed run **leaf-identical to the
fault-free run** (same seed, same FaultPlan replayed) or a structured,
named failure — never a hang, an uncaught traceback, or silent
divergence. The tier-1 subset (`-m chaos`, not slow) is the fast smoke:
one fault per class on a small world; the slow tier drives the same
matrix through the CLI and the hybrid worker fleet.
"""

import json
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest
import yaml

from test_pipeline import _assert_leaves_exact, _phold_world

from shadow_tpu.engine import EngineConfig
from shadow_tpu.engine.round import (
    EngineCompileError,
    WatchdogExpired,
    run_until,
)
from shadow_tpu.engine.state import state_to_host
from shadow_tpu.runtime import chaos
from shadow_tpu.runtime.checkpoint import (
    CheckpointError,
    CheckpointManager,
    load_checkpoint,
    peek_checkpoint_meta,
    save_checkpoint,
    verify_checkpoint,
)
from shadow_tpu.runtime.chaos import (
    FaultPlan,
    next_engine_cfg,
    parse_fault_arg,
    run_with_engine_ladder,
)
from shadow_tpu.runtime.cli_run import run_from_config, run_sweep
from shadow_tpu.runtime.recovery import RecoveryPolicy, run_until_recovering
from shadow_tpu.simtime import NS_PER_MS

pytestmark = pytest.mark.chaos


# ---- the FaultPlan determinism contract ---------------------------------


def test_fault_plan_deterministic_and_replayable():
    """Two plans from the same (seed, faults) fire at identical sites in
    identical order — including `at: auto` draws — and reset() restores
    the budgets so a chaos run can be replayed exactly."""
    faults = [
        {"kind": "capacity", "at": "auto"},
        {"kind": "stall", "at": 2, "stall_s": 0.1},
        {"kind": "compile", "target": "pump", "count": -1},
    ]
    a, b = FaultPlan(seed=9, faults=faults), FaultPlan(seed=9, faults=faults)
    assert [s.at for s in a.faults] == [s.at for s in b.faults]
    # a different seed draws a different schedule (over the kind+ordinal
    # stream, so two auto faults of one kind land independently)
    many = FaultPlan(
        seed=1,
        faults=[{"kind": "capacity", "at": "auto"} for _ in range(8)],
    )
    assert len({s.at for s in many.faults}) > 1
    # budget accounting: count=1 fires once, count=-1 forever
    assert a.should_fire("capacity", at=a.faults[0].at) is not None
    assert a.should_fire("capacity", at=a.faults[0].at) is None
    assert a.should_fire("compile", tags=("pump",)) is not None
    assert a.should_fire("compile", tags=("pump",)) is not None
    # target mismatch never fires, site mismatch never fires
    assert a.should_fire("compile", tags=("plain",)) is None
    assert a.should_fire("stall", at=0) is None
    a.reset()
    assert a.fired == []
    assert a.should_fire("capacity", at=a.faults[0].at) is not None
    assert a.report()["planned"] == 3 and len(a.report()["fired"]) == 1


def test_persistent_fault_report_stays_bounded():
    """A count=-1 fault fires once per chunk; the fired record list and
    the warning log must stay O(1) in run length — the report keeps the
    first MAX_FIRED_RECORDS records plus the true total."""
    plan = FaultPlan(faults=[{"kind": "capacity", "count": -1}])
    for i in range(chaos.MAX_FIRED_RECORDS + 50):
        assert plan.should_fire("capacity", at=i) is not None
    rep = plan.report()
    assert len(rep["fired"]) == chaos.MAX_FIRED_RECORDS
    assert rep["fired_total"] == chaos.MAX_FIRED_RECORDS + 50
    # small chaos runs keep the exact shape (no fired_total key)
    small = FaultPlan(faults=[{"kind": "capacity"}])
    small.should_fire("capacity", at=0)
    assert "fired_total" not in small.report()


def test_fire_without_plan_is_inert():
    chaos.uninstall()
    assert chaos.active() is None
    assert chaos.fire("capacity", at=0) is None
    with chaos.installed(FaultPlan(faults=[{"kind": "capacity"}])) as p:
        assert chaos.fire("capacity") is p.faults[0]
    assert chaos.active() is None


def test_parse_fault_arg():
    assert parse_fault_arg("capacity@2") == {"kind": "capacity", "at": 2}
    assert parse_fault_arg("stall@1:stall_s=0.5") == {
        "kind": "stall", "at": 1, "stall_s": 0.5,
    }
    assert parse_fault_arg("capacity:target=ph-s3:count=-1") == {
        "kind": "capacity", "target": "ph-s3", "count": -1,
    }
    assert parse_fault_arg("ckpt-corrupt@auto")["at"] == "auto"
    with pytest.raises(ValueError, match="unknown chaos fault kind"):
        parse_fault_arg("frobnicate@1")
    with pytest.raises(ValueError, match="key=val"):
        parse_fault_arg("capacity:count")
    with pytest.raises(ValueError, match="count must be"):
        parse_fault_arg("capacity:count=0")
    # the compile seams carry no site ordinal: a sited compile fault
    # would silently never fire, so it is rejected at parse time
    with pytest.raises(ValueError, match="no @AT site"):
        parse_fault_arg("compile@1")
    with pytest.raises(ValueError, match="no @AT site"):
        parse_fault_arg("compile@auto:target=pump")


def test_chaos_config_section_validates_values_eagerly():
    # the YAML path must fail at config load time with a one-line error
    # (-> CliUserError), matching the --chaos-fault path — never a
    # traceback mid-run when the FaultPlan is built
    from shadow_tpu.config.options import ChaosOptions

    for bad, match in (
        ({"kind": "capacity", "at": "soon"}, "invalid literal"),
        ({"kind": "capacity", "at": -1}, "at must be"),
        ({"kind": "capacity", "count": 0}, "count must be"),
        ({"kind": "stall", "stall_s": "fast"}, "could not convert"),
        ({"kind": "stall", "stall_s": -1}, "stall_s must be"),
    ):
        with pytest.raises(ValueError, match=match):
            ChaosOptions.from_dict({"faults": [bad]})
    # YAML-typical string values coerce cleanly
    ok = ChaosOptions.from_dict(
        {"faults": [{"kind": "stall", "at": "2", "stall_s": "0.5"}]}
    )
    assert ok.faults == [{"kind": "stall", "at": "2", "stall_s": "0.5"}]


# ---- the engine fallback ladder (megakernel -> pump -> plain) -----------


def _ecfg(engine, pump_k=3):
    return EngineConfig(
        num_hosts=2, queue_capacity=4, outbox_capacity=4, runahead_ns=1,
        seed=0, engine=engine, pump_k=pump_k,
    )


def test_next_engine_cfg_walks_the_ladder():
    assert next_engine_cfg(_ecfg("megakernel")).engine == "pump"
    assert next_engine_cfg(_ecfg("pump")).engine == "plain"
    assert next_engine_cfg(_ecfg("plain")) is None
    # "auto" resolves to what it would actually run before stepping down
    assert next_engine_cfg(_ecfg("auto", pump_k=3)).engine == "plain"
    assert next_engine_cfg(_ecfg("auto", pump_k=0)) is None


def test_engine_ladder_falls_to_plain_then_fails_structured():
    attempts = []

    def flaky(cfg):
        attempts.append(cfg.engine)
        if cfg.engine != "plain":
            raise EngineCompileError(cfg.engine, RuntimeError("boom"))
        return "done"

    result, fallbacks = run_with_engine_ladder(_ecfg("megakernel"), flaky)
    assert result == "done"
    assert attempts == ["megakernel", "pump", "plain"]
    assert [(f["from"], f["to"]) for f in fallbacks] == [
        ("megakernel", "pump"), ("pump", "plain"),
    ]
    assert "boom" in fallbacks[0]["reason"]

    def hopeless(cfg):
        raise EngineCompileError(cfg.engine, RuntimeError("bad lowering"))

    # the bottom rung failing is terminal — a typed, named failure
    with pytest.raises(EngineCompileError, match="plain"):
        run_with_engine_ladder(_ecfg("pump"), hopeless)


# ---- checkpoint integrity (sha-256 + fall-back-to-valid) ----------------


def test_checkpoint_corrupt_and_truncated_raise_named(tmp_path):
    cfg, model, tables, st0 = _phold_world()
    good = str(tmp_path / "ckpt-0001.npz")
    save_checkpoint(good, state_to_host(st0), {"fingerprint": "fp"})
    assert verify_checkpoint(good) is None
    assert peek_checkpoint_meta(good)["sha256"]

    corrupt = str(tmp_path / "corrupt.npz")
    trunc = str(tmp_path / "trunc.npz")
    for p in (corrupt, trunc):
        save_checkpoint(p, state_to_host(st0), {"fingerprint": "fp"})
    chaos.damage_file(corrupt, truncate=False)
    chaos.damage_file(trunc, truncate=True)
    for p in (corrupt, trunc):
        assert verify_checkpoint(p) is not None
        # never a bare zipfile.BadZipFile — a CheckpointError naming the file
        with pytest.raises(CheckpointError, match=p.replace("\\", ".")):
            load_checkpoint(p, st0, "fp")
    with pytest.raises(CheckpointError, match="corrupt or truncated"):
        peek_checkpoint_meta(trunc)


def test_checkpoint_sha256_catches_payload_tamper(tmp_path):
    """A leaf flipped WITHOUT breaking the zip structure is exactly what
    the digest exists for: the structural checks pass, the sha fails."""
    _cfg, _model, _tables, st0 = _phold_world()
    path = str(tmp_path / "ckpt-0001.npz")
    save_checkpoint(path, state_to_host(st0), {"fingerprint": "fp"})
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    leaf = arrays["leaf_00000"]
    arrays["leaf_00000"] = (leaf.astype(np.int64) + 1).astype(leaf.dtype)
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    assert verify_checkpoint(path) == "payload failed its sha-256 integrity check"
    with pytest.raises(CheckpointError, match="sha-256"):
        load_checkpoint(path, st0, "fp")


def test_latest_path_skips_corrupt_falls_back_to_valid(tmp_path):
    """One bad write can no longer take the whole resume path down: the
    newest-first walk skips damaged files with a warning and lands on
    the newest VALID checkpoint."""
    _cfg, _model, _tables, st0 = _phold_world()
    host = state_to_host(st0)
    older = str(tmp_path / "ckpt-00000000000000000001.npz")
    newer = str(tmp_path / "ckpt-00000000000000000002.npz")
    save_checkpoint(older, host, {"fingerprint": "fp"})
    save_checkpoint(newer, host, {"fingerprint": "fp"})
    chaos.damage_file(newer, truncate=True)
    assert CheckpointManager.latest_path(str(tmp_path)) == older
    # verify=False restores the raw lexical-newest lookup
    assert CheckpointManager.latest_path(str(tmp_path), verify=False) == newer
    chaos.damage_file(older, truncate=False)
    assert CheckpointManager.latest_path(str(tmp_path)) is None


def test_ckpt_faults_damage_manager_writes(tmp_path):
    """The ckpt-corrupt / ckpt-truncate chaos faults hit the Nth write of
    a CheckpointManager, after the atomic commit."""
    _cfg, _model, _tables, st0 = _phold_world()
    host = state_to_host(st0)
    plan = FaultPlan(faults=[{"kind": "ckpt-truncate", "at": 1}])
    with chaos.installed(plan):
        mgr = CheckpointManager(str(tmp_path), 0, "fp")
        p0 = mgr.write(host)
        host2 = host.replace(now=host.now + 1)
        p1 = mgr.write(host2)
    assert verify_checkpoint(p0) is None
    assert verify_checkpoint(p1) is not None
    assert plan.report()["fired"] == [{"kind": "ckpt-truncate", "at": 1}]
    assert CheckpointManager.latest_path(str(tmp_path)) == p0


# ---- signal robustness (pinning PR 4 behavior that was never tested) ----


def test_double_sigint_second_signal_aborts_immediately():
    """The first SIGINT sets the guard flag AND restores the previous
    handlers, so a second signal takes the default path (immediate
    KeyboardInterrupt — no second checkpoint attempt) instead of being
    swallowed by a wedged run. Run in a subprocess so the prev handler
    is Python's default, exactly as in a real CLI run."""
    code = (
        "import os, signal\n"
        "from shadow_tpu.runtime.checkpoint import InterruptGuard\n"
        "g = InterruptGuard()\n"
        "with g:\n"
        "    os.kill(os.getpid(), signal.SIGINT)\n"
        "    assert g.fired(0), 'first signal must arm the guard'\n"
        "    assert not g._prev, 'first signal must restore prev handlers'\n"
        "    try:\n"
        "        os.kill(os.getpid(), signal.SIGINT)\n"
        "        raise SystemExit('second SIGINT was swallowed')\n"
        "    except KeyboardInterrupt:\n"
        "        pass\n"
        "print('DOUBLE_SIGINT_OK')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        cwd=str(pathlib.Path(__file__).parent.parent),
        env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
    )
    assert "DOUBLE_SIGINT_OK" in r.stdout, r.stdout + r.stderr


def test_sigterm_mid_save_checkpoint_leaves_dir_loadable(tmp_path, monkeypatch):
    """A kill landing mid-save (modeled as the writer dying after partial
    tmp-file bytes) must leave the directory loadable: the atomic
    tmp+rename means the half-written file never takes the ckpt-*.npz
    name, and latest_path still returns the previous valid checkpoint."""
    from shadow_tpu.runtime import checkpoint as cp

    _cfg, _model, _tables, st0 = _phold_world()
    host = state_to_host(st0)
    mgr = CheckpointManager(str(tmp_path), 0, "fp")
    p0 = mgr.write(host)

    real_savez = np.savez

    def dying_savez(f, **arrays):
        f.write(b"PK\x03\x04 partial write, then SIGTERM")
        raise SystemExit(143)  # what SIGTERM's default disposition does

    monkeypatch.setattr(cp.np, "savez", dying_savez)
    with pytest.raises(SystemExit):
        mgr.write(host.replace(now=host.now + 1))
    monkeypatch.setattr(cp.np, "savez", real_savez)

    assert CheckpointManager.latest_path(str(tmp_path)) == p0
    restored, meta = load_checkpoint(p0, st0, "fp")
    _assert_leaves_exact(st0, restored)
    # the partial tmp file is present but invisible to the ckpt glob
    leftovers = list(pathlib.Path(tmp_path).glob("*.tmp.*"))
    assert leftovers, "the interrupted write should leave its tmp file"


# ---- engine-level matrix: injected faults end leaf-identical ------------


def test_stall_watchdog_redispatch_leaf_exact():
    """A stalled chunk dispatch blows the watchdog; the driver abandons
    the in-flight chunk and re-dispatches from the retained snapshot —
    and the final state is leaf-identical to the fault-free run (the
    watchdog path replays, never perturbs, the trajectory)."""
    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    # deadline well above a real chunk fetch on a loaded 1-core box (a
    # legitimate fetch blowing it would add a spurious recovery), well
    # below the injected stall so the fault reliably trips it
    plan = FaultPlan(faults=[{"kind": "stall", "at": 1, "stall_s": 2.5}])
    with chaos.installed(plan):
        final, recoveries = run_until_recovering(
            st0, end, model, tables, cfg, rounds_per_chunk=4,
            policy=RecoveryPolicy(max_recoveries=3, snapshot_interval_chunks=2),
            watchdog_s=0.75,
        )
    # ≥1 tolerates a contention-induced expiry riding along — the hard
    # contract is the kind, the injection record, and leaf-exactness
    kinds = [r["kind"] for r in recoveries]
    assert kinds and set(kinds) == {"watchdog"}
    assert recoveries[0]["deadline_s"] == 0.75
    assert plan.report()["fired"] == [{"kind": "stall", "at": 1}]
    _assert_leaves_exact(straight, final)


def test_watchdog_budget_exhausted_is_structured():
    """A persistent stall past the recovery budget surfaces as a typed
    WatchdogExpired naming the chunk and deadline — never a hang. The
    terminal exception carries the recoveries the run survived first, so
    a degraded-then-failed run stays visibly degraded (the sweep manifest
    reads this for quarantined jobs)."""
    cfg, model, tables, st0 = _phold_world()
    plan = FaultPlan(faults=[{"kind": "stall", "stall_s": 0.2, "count": -1}])
    with chaos.installed(plan):
        with pytest.raises(WatchdogExpired, match="watchdog deadline") as ei:
            run_until_recovering(
                st0, 40 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4,
                policy=RecoveryPolicy(max_recoveries=1),
                watchdog_s=0.05,
            )
    assert [r["kind"] for r in ei.value.recoveries] == ["watchdog"]


def test_injected_capacity_recovers_leaf_exact():
    """An injected CapacityError takes the real rollback-and-regrow path
    (tagged `injected` in the recovery record) and the completed run is
    leaf-exact vs a fault-free run that STARTED at the regrown capacity
    — the same exactness bar as a real overflow."""
    cfg, model, tables, st0 = _phold_world(queue_capacity=64)
    end = 40 * NS_PER_MS
    plan = FaultPlan(faults=[{"kind": "capacity", "at": 1}])
    with chaos.installed(plan):
        final, recoveries = run_until_recovering(
            st0, end, model, tables, cfg, rounds_per_chunk=4,
            policy=RecoveryPolicy(max_recoveries=2, snapshot_interval_chunks=2),
        )
    assert [r["kind"] for r in recoveries] == ["capacity"]
    assert recoveries[0]["injected"] is True
    assert final.queue.capacity == 128  # x2 growth ladder
    cfg2, model2, tables2, st2 = _phold_world(queue_capacity=128)
    reference = run_until(st2, end, model2, tables2, cfg2, rounds_per_chunk=4)
    _assert_leaves_exact(reference, final)


def test_compile_fault_falls_back_leaf_exact():
    """An injected compile fault on the pump engine walks the runtime
    ladder down to plain, and the completed run is leaf-identical to a
    straight plain run (the engines are leaf-exact by contract, so a
    fallback changes wall-clock, never a result leaf). The injection
    fires BEFORE the doomed engine compiles, so this smoke costs no
    extra executable."""
    import dataclasses

    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)

    pump_cfg = dataclasses.replace(cfg, engine="pump", pump_k=3)
    plan = FaultPlan(faults=[{"kind": "compile", "target": "pump"}])
    with chaos.installed(plan):
        final, fallbacks = run_with_engine_ladder(
            pump_cfg,
            lambda c: run_until(st0, end, model, tables, c, rounds_per_chunk=4),
        )
    assert [(f["from"], f["to"]) for f in fallbacks] == [("pump", "plain")]
    _assert_leaves_exact(straight, final)

    # a plain-engine compile failure has no rung left: structured error
    plain_plan = FaultPlan(faults=[{"kind": "compile", "target": "plain"}])
    with chaos.installed(plain_plan):
        with pytest.raises(EngineCompileError, match="plain"):
            run_with_engine_ladder(
                cfg,
                lambda c: run_until(
                    st0, end, model, tables, c, rounds_per_chunk=4
                ),
            )


def test_stall_without_watchdog_completes_identically():
    """Watchdog off: a stall is only a delay — the run completes with no
    recovery and a bit-identical final state."""
    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    straight = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    plan = FaultPlan(faults=[{"kind": "stall", "at": 1, "stall_s": 0.1}])
    with chaos.installed(plan):
        final = run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    _assert_leaves_exact(straight, final)


# ---- sweep path: poison-job quarantine (the acceptance pin) -------------


def _mini_sweep_service(retry_max: int):
    """A SweepService shell with just the state _handle_failure touches —
    the retry/quarantine ladder is pure bookkeeping, so it unit-tests
    without building a world or compiling anything."""
    import types

    from shadow_tpu.runtime.sweep import SweepService

    svc = SweepService.__new__(SweepService)
    svc.spec = types.SimpleNamespace(retry_max=retry_max, retry_backoff_s=0.0)
    svc.clock_ns = 0
    svc.job_attempts = {}
    svc.job_records = {}
    svc.job_progress = {"j0": {"now_ns": 0, "events": 0}}
    svc.batches = []
    return svc


def _mini_job_batch():
    import types

    from shadow_tpu.runtime.sweep import Batch

    job = types.SimpleNamespace(
        name="j0", entry="e", seed=1, priority=0, arrival_ns=0,
        group_key="g" * 16,
        config=types.SimpleNamespace(
            general=types.SimpleNamespace(data_directory="d")
        ),
    )
    batch = Batch(
        jobs=[job], base_seed=1, stride=1, priority=0, arrival_ns=0,
        group_key=job.group_key, index=0,
    )
    return job, batch


def test_sweep_failure_terminal_status_failed_vs_quarantined():
    """The ladder's terminal statuses: `quarantined` is reserved for a
    repeat offender (failed again after a retry); with retry_max: 0 the
    first failure is terminal and the job is recorded plain `failed` —
    both count against the exit code (docs/service.md)."""
    err = ValueError("boom")

    # retry_max=0: never retried, so never a "repeat offender"
    svc = _mini_sweep_service(retry_max=0)
    job, batch = _mini_job_batch()
    svc._handle_failure(batch, err, pending=[])
    rec = svc.job_records["j0"]
    assert rec["status"] == "failed"
    assert rec["failure"] == "ValueError"
    assert rec["failed_attempts"] == 1

    # retry_max=1: first failure re-queues, second quarantines
    svc = _mini_sweep_service(retry_max=1)
    job, batch = _mini_job_batch()
    pending: list = []
    svc._handle_failure(batch, err, pending)
    assert "j0" not in svc.job_records and len(pending) == 1  # retried
    svc._handle_failure(pending.pop(), err, pending)
    rec = svc.job_records["j0"]
    assert rec["status"] == "quarantined"
    assert rec["failed_attempts"] == 2


def test_sweep_untyped_batch_error_walks_ladder_not_abort():
    """An UNTYPED runtime error in one batch (an XLA device error, a bug
    in our own code) must walk the same split/retry/quarantine ladder as
    the typed kinds — never abort the sweep before the manifest is
    written, voiding the other N−1 jobs with a bare traceback."""
    svc = _mini_sweep_service(retry_max=0)
    job, batch = _mini_job_batch()

    def boom(b, pending):
        raise RuntimeError("XLA runtime error: RESOURCE_EXHAUSTED")

    svc._run_batch = boom
    svc._drain([batch])  # must NOT raise
    rec = svc.job_records["j0"]
    assert rec["status"] == "failed"
    assert rec["failure"] == "RuntimeError"
    assert "RESOURCE_EXHAUSTED" in rec["error"]


SWEEP_BASE = """
general:
  stop_time: 80 ms
  heartbeat_interval: null
network:
  graph:
    type: 1_gbit_switch
experimental:
  rounds_per_chunk: 4
  recover: false
hosts:
  peer:
    network_node_id: 0
    quantity: 8
    processes:
      - path: phold
        args:
          min_delay: "2 ms"
          max_delay: "12 ms"
"""

SWEEP_JOBS = """
  jobs:
    - name: ph
      seed_range: [0, 8]
"""


def _sweep_spec(tmp_path, name, base_name, out):
    spec = tmp_path / f"{name}.yaml"
    spec.write_text(
        f"sweep:\n  name: {name}\n  base: {base_name}\n"
        f"  output_dir: {out}\n  retry_max: 1\n{SWEEP_JOBS}"
    )
    return spec


@pytest.fixture(scope="module")
def fault_free_sweep(tmp_path_factory):
    """The fault-free 8-job reference sweep the poison run must match."""
    root = tmp_path_factory.mktemp("chaos-sweep")
    (root / "base.yaml").write_text(SWEEP_BASE)
    out = root / "clean"
    assert run_sweep(str(_sweep_spec(root, "clean", "base.yaml", out))) == 0
    return root, json.loads((out / "sweep-manifest.json").read_text())


@pytest.mark.slow
def test_sweep_poison_job_quarantined_rest_identical(fault_free_sweep):
    """THE acceptance pin: an 8-job sweep with one poison job (persistent
    injected CapacityError targeting ph-s3) completes the other 7 jobs
    with sim-stats identical to the fault-free sweep, quarantines the
    poison job in sweep-manifest.json with its failure kind, and exits
    non-zero."""
    root, clean = fault_free_sweep
    base = yaml.safe_load(SWEEP_BASE)
    base["chaos"] = {
        "faults": [
            {"kind": "capacity", "at": 1, "target": "ph-s3", "count": -1}
        ]
    }
    (root / "poison.yaml").write_text(yaml.dump(base))
    out = root / "poisoned"
    rc = run_sweep(str(_sweep_spec(root, "poisoned", "poison.yaml", out)))
    assert rc == 1  # a quarantined job must fail the process
    m = json.loads((out / "sweep-manifest.json").read_text())
    assert m["jobs_total"] == 8 and m["jobs_done"] == 7
    assert m["jobs_quarantined"] == 1 and m["jobs_failed"] == 0

    by_name = {r["name"]: r for r in m["jobs"]}
    poison = by_name["ph-s3"]
    assert poison["status"] == "quarantined"
    assert poison["failure"] == "capacity"
    assert poison["failed_attempts"] == 2  # first solo try + retry_max=1
    assert "injected" in poison["error"]
    # the original packed batch split; the poison job's retries failed
    statuses = {b["status"] for b in m["batches"]}
    assert "split" in statuses and "failed" in statuses
    # the chaos section makes the injection visible in the manifest
    assert all(f["target"] == "ph-s3" for f in m["chaos"]["fired"])

    clean_by_name = {r["name"]: r for r in clean["jobs"]}
    for name, rec in by_name.items():
        if name == "ph-s3":
            continue
        assert rec["status"] == "done"
        assert rec["stats"] == clean_by_name[name]["stats"], name
        # published per-job sim-stats match the fault-free sweep's too
        poisoned_stats = json.loads(
            (out / "jobs" / name / "sim-stats.json").read_text()
        )
        clean_stats = json.loads(
            (root / "clean" / "jobs" / name / "sim-stats.json").read_text()
        )
        for s in (poisoned_stats, clean_stats):
            s.pop("wall_seconds")
        assert poisoned_stats == clean_stats, name


@pytest.mark.slow
def test_sweep_preempt_storm_changes_nothing(fault_free_sweep):
    """A chaos `preempt` storm (guard armed twice with no higher-priority
    arrival) forces checkpoint/requeue/resume cycles — and every job's
    published stats still match the fault-free sweep, because each
    resume is bit-exact."""
    root, clean = fault_free_sweep
    base = yaml.safe_load(SWEEP_BASE)
    base["chaos"] = {"faults": [{"kind": "preempt", "at": 2, "count": 2}]}
    (root / "stormbase.yaml").write_text(yaml.dump(base))
    out = root / "storm"
    spec = root / "storm.yaml"
    spec.write_text(
        f"sweep:\n  name: storm\n  base: stormbase.yaml\n"
        f"  output_dir: {out}\n  retry_max: 1\n"
        "  jobs:\n    - name: ph\n      seeds: [0, 1]\n"
    )
    assert run_sweep(str(spec)) == 0
    m = json.loads((out / "sweep-manifest.json").read_text())
    assert m["jobs_done"] == 2 and m["preemptions"] == 2
    assert len(m["chaos"]["fired"]) == 2
    clean_by_name = {r["name"]: r for r in clean["jobs"]}
    for r in m["jobs"]:
        assert r["status"] == "done"
        assert r["stats"] == clean_by_name[r["name"]]["stats"], r["name"]


# ---- CLI-level matrix: one fault per class through shadow-tpu run -------

CLI_BASE = """
general:
  stop_time: 100 ms
  heartbeat_interval: null
network:
  graph:
    type: 1_gbit_switch
experimental:
  rounds_per_chunk: 4
hosts:
  peer:
    network_node_id: 0
    quantity: 8
    processes:
      - path: phold
        args:
          min_delay: "2 ms"
          max_delay: "12 ms"
"""

_CORE_KEYS = (
    "events_handled", "packets_sent", "packets_dropped",
    "packets_unroutable", "num_hosts",
)


def _cli_run(root, tag, chaos_cfg=None, experimental=None, general=None):
    cfg = yaml.safe_load(CLI_BASE)
    cfg["general"]["data_directory"] = str(root / tag)
    if general:
        cfg["general"].update(general)
    if experimental:
        cfg["experimental"].update(experimental)
    if chaos_cfg:
        cfg["chaos"] = chaos_cfg
    path = root / f"{tag}.yaml"
    path.write_text(yaml.dump(cfg))
    rc = run_from_config(str(path))
    stats_path = root / tag / "sim-stats.json"
    # an interrupted run (exit 130) stops before writing sim-stats.json
    stats = json.loads(stats_path.read_text()) if stats_path.exists() else None
    return rc, stats


@pytest.mark.slow
def test_chaos_matrix_cli_run_path(tmp_path):
    """One injected fault per engine-facing class through the real CLI
    entry point: every run completes with core stats identical to the
    fault-free baseline, exits 0, and publishes chaos + degraded
    sections — a degraded run is visibly degraded, never silently
    slower or quietly wrong."""
    rc0, s0 = _cli_run(tmp_path, "baseline")
    assert rc0 == 0
    core0 = {k: s0[k] for k in _CORE_KEYS}
    assert "chaos" not in s0 and "degraded" not in s0

    # stall -> watchdog re-dispatch (deadline well above a real chunk
    # fetch on a loaded box, well below the injected stall; ≥1 tolerates
    # a contention-induced expiry riding along — the hard contract is
    # identical core stats plus a visibly degraded report)
    rc, s = _cli_run(
        tmp_path, "stall",
        chaos_cfg={"faults": [{"kind": "stall", "at": 1, "stall_s": 2.5}]},
        experimental={"chunk_watchdog_s": 0.75},
    )
    assert rc == 0 and {k: s[k] for k in _CORE_KEYS} == core0
    assert s["degraded"]["watchdog_redispatches"] >= 1
    assert s["recovery"]["events"][0]["kind"] == "watchdog"
    assert s["chaos"]["fired"] == [{"kind": "stall", "at": 1}]

    # compile failure -> engine fallback ladder (pump -> plain)
    rc, s = _cli_run(
        tmp_path, "compile",
        chaos_cfg={"faults": [{"kind": "compile", "target": "pump"}]},
        experimental={"engine": "pump", "pump_k": 4},
    )
    assert rc == 0 and {k: s[k] for k in _CORE_KEYS} == core0
    assert s["degraded"]["engine_fallbacks"] == [{
        "from": "pump", "to": "plain",
        "reason": "injected fault: pump engine compile failed (chaos plane)",
    }]

    # injected capacity -> rollback-and-regrow, tagged injected
    rc, s = _cli_run(
        tmp_path, "capacity",
        chaos_cfg={"faults": [{"kind": "capacity", "at": 1}]},
    )
    assert rc == 0 and {k: s[k] for k in _CORE_KEYS} == core0
    assert s["recovery"]["count"] == 1
    assert s["recovery"]["events"][0]["injected"] is True


@pytest.mark.slow
def test_chaos_matrix_cli_resume_path(tmp_path, monkeypatch):
    """Resume path: the run is interrupted mid-flight and its FINAL
    checkpoint is truncated by an injected fault — resume must fall back
    to the previous valid checkpoint with a warning and still reach the
    fault-free final stats."""
    rc0, s0 = _cli_run(tmp_path, "baseline")
    core0 = {k: s0[k] for k in _CORE_KEYS}

    monkeypatch.setenv("SHADOW_TPU_TEST_INTERRUPT_AT_NS", str(50 * NS_PER_MS))
    ckpt_dir = str(tmp_path / "ckpts")
    rc, _ = _cli_run(
        tmp_path, "interrupted",
        chaos_cfg={"faults": [{"kind": "ckpt-truncate", "at": 2}]},
        general={"checkpoint_dir": ckpt_dir, "checkpoint_interval": "20 ms"},
    )
    assert rc == 130  # interrupted-with-checkpoint exit status
    damaged = [
        p for p in pathlib.Path(ckpt_dir).glob("ckpt-*.npz")
        if verify_checkpoint(str(p)) is not None
    ]
    assert len(damaged) == 1, "the final checkpoint write must be truncated"

    monkeypatch.delenv("SHADOW_TPU_TEST_INTERRUPT_AT_NS")
    rc, s = _cli_run(
        tmp_path, "resumed",
        general={
            "checkpoint_dir": ckpt_dir, "checkpoint_interval": "20 ms",
            "resume": True,
        },
    )
    assert rc == 0 and {k: s[k] for k in _CORE_KEYS} == core0


# ---- hybrid worker faults: kill / hang under supervision ----------------


def test_worker_fault_injection_seam():
    """Tier-1 smoke for the worker-kill / worker-hang classes: the
    injection seam SIGKILLs / SIGSTOPs exactly the targeted worker
    process (full supervision equivalence runs in the slow tier)."""
    import multiprocessing as mp
    import os
    import signal as sig
    import types

    from shadow_tpu.runtime.hybrid import ParallelHybridScheduler

    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=time.sleep, args=(60,)) for _ in range(2)]
    for p in procs:
        p.start()
    stub = types.SimpleNamespace(
        _workers=[(p, None) for p in procs], _windows_sent=0
    )
    inject = ParallelHybridScheduler._inject_worker_faults
    try:
        # no plan installed: a no-op
        inject(stub)
        assert all(p.is_alive() for p in procs)
        plan = FaultPlan(faults=[
            {"kind": "worker-kill", "at": 0, "target": "worker1"},
            {"kind": "worker-hang", "at": 0, "target": "worker0"},
        ])
        with chaos.installed(plan):
            inject(stub)
        procs[1].join(10)
        assert not procs[1].is_alive(), "worker1 must be SIGKILLed"
        assert procs[0].is_alive(), "worker0 is stopped, not dead"
        state = pathlib.Path(f"/proc/{procs[0].pid}/stat").read_text()
        assert state.split()[2] == "T", "worker0 must be SIGSTOPped"
        assert sorted(f["kind"] for f in plan.report()["fired"]) == [
            "worker-hang", "worker-kill",
        ]
    finally:
        for p in procs:
            if p.is_alive():
                os.kill(p.pid, sig.SIGKILL)
            p.join(10)


GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def hybrid_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("chaos-guests")
    built = {}
    for name in ("tcp_echo_server", "tcp_client"):
        dst = out / name
        subprocess.run(
            ["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True
        )
        built[name] = str(dst)
    return built


def _run_hybrid(tmp_path, bins, name, plan=None, **kw):
    """One hybrid run under an optional fault plan; returns the
    cross-run-comparable outcome tuple (stats, sorted event log, guest
    info, respawn counters) — the same equivalence surface
    tests/test_hybrid_supervision.py pins."""
    from shadow_tpu.graph import compute_routing
    from shadow_tpu.hostk.kernel import ProcessSpec
    from shadow_tpu.runtime.hybrid import ParallelHybridScheduler
    from shadow_tpu.simtime import NS_PER_SEC
    from tests.topo import two_node_graph

    graph = two_node_graph(10, 0.0)
    host_names, host_nodes = ["server0", "client0"], [0, 1]
    tables = compute_routing(graph).with_hosts(host_nodes)
    cfg = EngineConfig(
        num_hosts=2, queue_capacity=256, outbox_capacity=64,
        runahead_ns=1 * NS_PER_MS, seed=5,
    )
    specs = [
        ProcessSpec(host="server0", args=[bins["tcp_echo_server"], "8080", "1"]),
        ProcessSpec(
            host="client0",
            args=[bins["tcp_client"], "server0", "8080", "6000"],
            start_ns=100 * NS_PER_MS,
        ),
    ]
    sched = ParallelHybridScheduler(
        tables, cfg, host_names=host_names, host_nodes=host_nodes,
        specs=specs, num_workers=2, seed=5, data_dir=tmp_path / name, **kw,
    )
    ctx = chaos.installed(plan) if plan is not None else chaos.installed(None)
    with ctx:
        try:
            try:
                sched.run(30 * NS_PER_SEC)
            finally:
                sched.shutdown()
            stats = sched.stats()
            log = sorted(sched.event_log())
            info = {
                p["host"]: (p["stdout"], p["exit_code"], p["syscalls"])
                for p in sched.proc_info()
            }
            return stats, log, info, list(sched._respawns)
        finally:
            sched.close()


@pytest.mark.slow
def test_worker_kill_and_hang_faults_recover_identically(tmp_path, hybrid_bins):
    """The worker-kill and worker-hang chaos faults land on the real
    supervision path (bounded recv -> kill -> respawn -> replay) and the
    run's outcomes are identical to an undisturbed run — the in-process
    twin of the SIGKILL harness tests/test_hybrid_supervision.py uses."""
    clean = _run_hybrid(tmp_path, hybrid_bins, "clean")
    assert clean[3] == [0, 0]

    kill_plan = FaultPlan(
        faults=[{"kind": "worker-kill", "at": 1, "target": "worker1"}]
    )
    killed = _run_hybrid(tmp_path, hybrid_bins, "killed", plan=kill_plan)
    assert killed[3] == [0, 1]  # exactly one respawn, of the killed worker
    assert kill_plan.report()["fired"] == [
        {"kind": "worker-kill", "at": 1, "target": "worker1"}
    ]
    assert killed[:3] == clean[:3]

    hang_plan = FaultPlan(
        faults=[{"kind": "worker-hang", "at": 1, "target": "worker1"}]
    )
    t0 = time.monotonic()
    hung = _run_hybrid(
        tmp_path, hybrid_bins, "hung", plan=hang_plan, rpc_timeout_s=5,
    )
    assert hung[3] == [0, 1]  # the hung worker was killed + respawned
    assert hung[:3] == clean[:3]
    assert time.monotonic() - t0 < 300  # bounded: never an indefinite hang
