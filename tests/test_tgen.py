"""tgen traffic-generator model tests: repeated request/response streams
over the TCP stack (the reference's tgen matrix workloads, src/test/tgen/)."""

import numpy as np
import pytest

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, run_until
from shadow_tpu.graph import compute_routing
from shadow_tpu.models.tgen import TgenModel
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC
from tests.topo import two_node_graph


def _setup(clients=2, servers=2, resp=20_000, pause_ms=100, loss=0.0, seed=3):
    num_hosts = clients + servers
    graph = two_node_graph(latency_ms=10, loss=loss)
    host_node = [0] * clients + [1] * servers
    tables = compute_routing(graph).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=512,
        outbox_capacity=128,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
    )
    model = TgenModel(
        num_hosts=num_hosts,
        num_clients=clients,
        num_servers=servers,
        resp_bytes=resp,
        pause_ns=pause_ms * NS_PER_MS,
    )
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    return cfg, model, tables, st


@pytest.mark.parametrize("loss", [0.0, 0.02])
def test_streams_cycle(loss):
    clients, resp = 2, 20_000
    cfg, model, tables, st = _setup(clients=clients, resp=resp, loss=loss)
    st = run_until(st, 10 * NS_PER_SEC, model, tables, cfg, rounds_per_chunk=64, max_chunks=50_000)

    done = np.asarray(st.model.streams_done)[:clients]
    down = np.asarray(st.model.bytes_down)[:clients]
    # each client cycles multiple streams in 10 s of sim time
    assert (done >= 3).all(), done
    # every completed stream delivered the full response
    assert (down >= done * resp).all(), (down, done)
    assert int(np.asarray(st.model.resets).sum()) == 0
    assert int(st.queue.overflow.sum()) == 0
    assert int(st.outbox.overflow.sum()) == 0


def test_streams_deterministic():
    cfg, model, tables, st0 = _setup(loss=0.03, seed=11)
    a = run_until(st0, 5 * NS_PER_SEC, model, tables, cfg, rounds_per_chunk=64, max_chunks=50_000)
    b = run_until(st0, 5 * NS_PER_SEC, model, tables, cfg, rounds_per_chunk=64, max_chunks=50_000)
    for name in ("streams_done", "streams_started", "bytes_down"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.model, name)), np.asarray(getattr(b.model, name))
        )
    np.testing.assert_array_equal(np.asarray(a.packets_sent), np.asarray(b.packets_sent))


def test_many_to_few_servers():
    # 6 clients share 2 servers round-robin
    cfg, model, tables, st = _setup(clients=6, servers=2, resp=10_000, pause_ms=200)
    st = run_until(st, 8 * NS_PER_SEC, model, tables, cfg, rounds_per_chunk=64, max_chunks=50_000)
    done = np.asarray(st.model.streams_done)[:6]
    assert (done >= 2).all(), done
