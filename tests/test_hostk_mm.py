"""Memory-map ledger + >64KB IO on virtual fds (round-2 verdict item 5):
the shim chunks large write/writev transparently (one guest call, full
count back) and reports every mmap/munmap/brk to the kernel's per-process
address-space ledger (the bookkeeping role of the reference's
MemoryManager, memory_manager/mod.rs:1-17). The guest's stdout must match
a native run byte for byte."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def mm_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("mm") / "mm_guest"
    subprocess.run(["cc", "-O2", "-o", str(out), str(GUESTS / "mm_guest.c")], check=True)
    return str(out)


def _native(mm_bin, tmp_path):
    d = tmp_path / "native"
    d.mkdir()
    r = subprocess.run([mm_bin], capture_output=True, cwd=d)
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()
    return r.stdout


def _shadow(mm_bin, tmp_path):
    graph = two_node_graph(10, 0.0)
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(
        tables, host_names=["h"], host_nodes=[0], data_dir=tmp_path / "shadow"
    )
    p = k.add_process(ProcessSpec(host="h", args=[mm_bin]))
    try:
        k.run(30 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, p


def test_mm_guest_matches_native(tmp_path, mm_bin):
    native_out = _native(mm_bin, tmp_path)
    k, p = _shadow(mm_bin, tmp_path)
    assert p.exit_code == 0, p.stdout().decode() + p.stderr().decode()
    assert p.stdout() == native_out
    assert b"mm all ok" in p.stdout()


def test_mm_ledger_tracks_guest_mappings(tmp_path, mm_bin):
    k, p = _shadow(mm_bin, tmp_path)
    assert p.exit_code == 0
    # the 256 KB file mapping is still live at exit; the 1 MB anon one was
    # unmapped and must be gone
    live = sorted(p.mappings.values())
    assert any(ln == 256 * 1024 for (ln, *_rest) in live), live
    assert not any(ln == 1 << 20 for (ln, *_rest) in live), live
    # the break moved (sbrk growth was reported)
    assert p.brk_end > 0
    # strace saw the mm traffic
    names = [s for _, s, _ in p.syscall_log]
    assert "mmap" in names
