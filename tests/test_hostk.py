"""Managed-process kernel tests: real compiled binaries under the
LD_PRELOAD shim, exchanging UDP through the simulated network on
simulated time (the analogue of the reference's add_shadow_tests paired
suites, src/test/CMakeLists.txt:35-62, run against real executables)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import compute_routing
from tests.topo import two_node_graph
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC, SIM_START_UNIX_NS

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def guest_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    bins = {}
    for name in ("udp_echo", "udp_client"):
        dst = out / name
        subprocess.run(
            ["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True
        )
        bins[name] = str(dst)
    return bins



def _kernel(tmp_path, latency_ms=10, loss=0.0, seed=1):
    graph = two_node_graph(latency_ms, loss)
    tables = compute_routing(graph).with_hosts([0, 1])
    return NetKernel(
        tables,
        host_names=["server", "client"],
        host_nodes=[0, 1],
        seed=seed,
        data_dir=tmp_path / "data",
    )


def _run_echo_sim(tmp_path, guest_bins, n=5, latency_ms=10, seed=1, subdir="a"):
    k = _kernel(tmp_path / subdir, latency_ms=latency_ms, seed=seed)
    server_ip = "11.0.0.1"
    srv = k.add_process(ProcessSpec(host="server", args=[guest_bins["udp_echo"], "7000", str(n)]))
    cli = k.add_process(
        ProcessSpec(
            host="client",
            args=[guest_bins["udp_client"], server_ip, "7000", str(n), "5"],
            start_ns=100 * NS_PER_MS,
        )
    )
    try:
        k.run(5 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, srv, cli


def test_udp_echo_under_simulated_network(tmp_path, guest_bins):
    n = 5
    k, srv, cli = _run_echo_sim(tmp_path, guest_bins, n=n)

    assert srv.state == "exited" and cli.state == "exited"
    srv_out = srv.stdout().decode()
    cli_out = cli.stdout().decode()
    assert srv_out.count("echo ") == n
    assert "server done" in srv_out
    assert cli_out.count("rtt ") == n

    # RTTs observed on the *simulated* clock: 2 x 10 ms link latency plus
    # a handful of 1 us syscall charges — far from wall time, tightly bounded
    for line in cli_out.splitlines():
        if line.startswith("rtt "):
            rtt = int(line.split()[2])
            assert 20 * NS_PER_MS <= rtt < 21 * NS_PER_MS, line
    # replies echo the payload back unmodified
    assert "reply=ping-0" in cli_out and f"reply=ping-{n-1}" in cli_out


def test_guest_clock_starts_at_sim_epoch(tmp_path, guest_bins):
    k, srv, cli = _run_echo_sim(tmp_path, guest_bins, n=2, subdir="epoch")
    # 2000-01-01 epoch (reference emulated_time.rs:25-34): guest timestamps
    # must sit just after SIM_START_UNIX_NS, regardless of the real date
    for line in srv.stdout().decode().splitlines():
        if line.startswith("echo "):
            sec = int(line.rsplit("t=", 1)[1].split(".")[0])
            assert abs(sec - SIM_START_UNIX_NS // NS_PER_SEC) < 10, line


def test_deterministic_across_runs(tmp_path, guest_bins):
    a = _run_echo_sim(tmp_path, guest_bins, n=4, subdir="r1")
    b = _run_echo_sim(tmp_path, guest_bins, n=4, subdir="r2")
    # identical guest-visible outputs (timestamps included) and event logs
    assert a[1].stdout() == b[1].stdout()
    assert a[2].stdout() == b[2].stdout()
    assert a[0].event_log == b[0].event_log
    assert [s for _, s, _ in a[2].syscall_log] == [s for _, s, _ in b[2].syscall_log]


def test_exit_codes_reaped(tmp_path, guest_bins):
    k, srv, cli = _run_echo_sim(tmp_path, guest_bins, n=3, subdir="exit")
    assert srv.exit_code == 0
    assert cli.exit_code == 0
