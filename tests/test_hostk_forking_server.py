"""Multi-process-server grade: socketserver.ForkingTCPServer (stock
CPython) forks one child per connection and serves N distro-curl clients
in-sim, run-twice deterministic — the round-4 verdict's acceptance bar
for syscall breadth (Next #3). Exercises per-connection fork, parent
wait4/SIGCHLD reaping, inherited virtual sockets across fork, and
ioctl(FIONBIO) (CPython's settimeout path).

Reference analogue: preforking servers under
/root/reference/src/main/host/syscall_handler.c dispatch breadth (fork
rows) + the nginx/curl example matrix (src/test/examples/)."""

import json
import os

import pytest

from shadow_tpu.runtime.cli_run import run_from_config

PY = "/usr/bin/python3"
CURL = "/usr/bin/curl"

pytestmark = pytest.mark.skipif(
    not (os.access(PY, os.X_OK) and os.access(CURL, os.X_OK)),
    reason="system python3/curl missing",
)

SERVER_PY = r"""
import http.server, socketserver, sys

class H(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def do_GET(self):
        import os
        body = ("forked pid=%d path=%s\n" % (os.getpid(), self.path)).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def log_message(self, fmt, *args):
        sys.stderr.write("%s - %s\n" % (self.address_string(), fmt % args))

class Srv(socketserver.ForkingTCPServer):
    allow_reuse_address = True

with Srv(("0.0.0.0", 80), H) as srv:
    sys.stdout.write("ready\n"); sys.stdout.flush()
    srv.serve_forever()
"""

CONFIG = """
general:
  stop_time: 12 s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {py}
        args: ["-u", "{server_py}"]
        expected_final_state: running
  client1:
    network_node_id: 0
    processes:
      - path: {curl}
        args: ["-sS", "--max-time", "5", "-o", "page.txt", "http://server/c1"]
        start_time: 3 s
  client2:
    network_node_id: 0
    processes:
      - path: {curl}
        args: ["-sS", "--max-time", "5", "-o", "page.txt", "http://server/c2"]
        start_time: 3500 ms
  client3:
    network_node_id: 0
    processes:
      - path: {curl}
        args: ["-sS", "--max-time", "5", "-o", "page.txt", "http://server/c3"]
        start_time: 4 s
"""


def _run(tmp_path, sub):
    d = tmp_path / sub
    d.mkdir(parents=True)
    server_py = d / "forksrv.py"
    server_py.write_text(SERVER_PY)
    cfg = d / "shadow.yaml"
    cfg.write_text(
        CONFIG.format(data_dir=d / "data", py=PY, curl=CURL, server_py=server_py)
    )
    rc = run_from_config(str(cfg))
    return rc, d / "data"


def _transcript(data):
    """The determinism-relevant transcript of one run."""
    out = {}
    for c in ("client1", "client2", "client3"):
        out[c] = (data / c / "page.txt").read_bytes()
    out["server_stdout"] = next((data / "server").glob("*.stdout")).read_bytes()
    return out


def test_forking_server_serves_three_curls(tmp_path):
    rc, data = _run(tmp_path, "a")
    assert rc == 0
    pids = set()
    for c in ("client1", "client2", "client3"):
        body = (data / c / "page.txt").read_text()
        assert f"path=/c{c[-1]}" in body
        pids.add(body.split("pid=")[1].split()[0])
    # each connection was handled by a DIFFERENT forked child
    assert len(pids) == 3
    # the parent reaped its children (wait4 path) and kept serving
    stats = json.loads((data / "sim-stats.json").read_text())
    assert stats["syscall_counts"].get("wait4", 0) >= 3
    assert stats["syscall_counts"].get("fork", 0) == 3


def test_forking_server_deterministic(tmp_path):
    t1 = None
    for sub in ("r1", "r2"):
        rc, data = _run(tmp_path, sub)
        assert rc == 0
        t = _transcript(data)
        if t1 is None:
            t1 = t
        else:
            assert t == t1
