"""Go-runtime thread patterns under the managed kernel (round-3 verdict
Next #4; reference acceptance: src/test/golang/test_goroutines.go — no Go
toolchain ships on this image, so the guest reproduces the runtime-level
mechanics in C): raw clone Ms with CLONE_CHILD_SETTID/CLEARTID, virtual
tids in the settid words, ctid-futex join against the simulated futex
table, per-thread sigaltstack, and cross-thread SIGURG preemption IPIs
aimed by virtual tid at threads spinning in compute."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def go_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("go") / "go_patterns_guest"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(GUESTS / "go_patterns_guest.c")],
        check=True,
    )
    return str(out)


def _run(tmp_path, go_bin, sub):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / sub)
    p = k.add_process(ProcessSpec(host="box", args=[go_bin]))
    try:
        k.run(30 * NS_PER_SEC)
    finally:
        k.shutdown()
    return p


def test_go_patterns(tmp_path, go_bin):
    p = _run(tmp_path, go_bin, "a")
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "settid ok 1" in out
    assert "joined 2" in out
    assert "preempts ok 1" in out
    assert "spun ok 1" in out
    assert "go patterns all ok" in out


def test_go_patterns_deterministic_counts(tmp_path, go_bin):
    """Preemption delivery is asynchronous (native IPIs, like the
    reference's host-signal interrupts), so exact timing varies — the
    *observable protocol results* (settid values, joins, delivery counts
    reaching the stop threshold) must be stable across runs."""
    a = _run(tmp_path, go_bin, "r1").stdout()
    b = _run(tmp_path, go_bin, "r2").stdout()
    assert a == b
