"""Memory observatory (docs/observability.md; runtime/memtrack.py).

The contracts under test:

  * **exact static pricing** — price_state's total equals the literal
    sum of leaf nbytes (typed PRNG keys priced as their raw key words)
    on all three planes (single, ensemble [R], mesh — which shares the
    ensemble pytree), and abstract jax.eval_shape pytrees price
    identically to concrete ones, so `shadow-tpu mem` never allocates;
  * **exact regrow projection** — price_regrow matches what grow_state
    actually allocates, and max_hosts_for_budget is monotone;
  * **zero extra device syncs** — the flight recorder's device-memory
    sampling is a pure host call: not one `jax.device_get`, and a
    backend without memory_stats (CPU) disables itself after one probe;
  * **priced failures** — a CapacityError carries the saturated
    buffer's current/post-regrow bytes, and a capacity recovery record
    carries the full state's priced current/post-regrow bytes.
"""

import json
import pathlib
import sys

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent))
from test_pipeline import _phold_world  # noqa: E402

from shadow_tpu.engine.state import (  # noqa: E402
    fmt_bytes,
    grow_state,
    init_state,
    leaf_nbytes,
    tree_nbytes,
)
from shadow_tpu.runtime import memtrack  # noqa: E402
from shadow_tpu.simtime import NS_PER_MS  # noqa: E402

pytestmark = pytest.mark.metrics


def _manual_nbytes(tree) -> int:
    """The reference total: literal leaf nbytes, typed PRNG key leaves
    measured as their raw key words (independent of leaf_nbytes)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        try:
            total += leaf.nbytes
        except Exception:  # typed PRNG key arrays
            total += jax.random.key_data(leaf).nbytes
    return int(total)


# ---- static pricing exactness -------------------------------------------


def test_price_state_exact_single_plane():
    cfg, _model, _tables, st0 = _phold_world()
    report = memtrack.price_state(st0, cfg)
    assert report["total_bytes"] == _manual_nbytes(st0) == tree_nbytes(st0)
    assert report["num_hosts"] == cfg.num_hosts
    assert report["replicas"] == 1
    # group totals partition the state: nothing dropped, nothing counted
    # twice
    assert sum(g["bytes"] for g in report["groups"].values()) == report[
        "total_bytes"
    ]
    # the dominant grid on any phold world is the queue's [H, C] rows
    assert report["dominant"]["name"].startswith("queue.")


def test_price_state_exact_ensemble_and_mesh_planes():
    from shadow_tpu.engine.ensemble import init_ensemble_state
    from shadow_tpu.engine.mesh import MeshPlan, init_mesh_state

    cfg, model, _tables, _st0 = _phold_world(num_hosts=4)
    ens = init_ensemble_state(cfg, model, 3, 1)
    rep = memtrack.price_state(ens, cfg)
    assert rep["total_bytes"] == _manual_nbytes(ens)
    assert rep["replicas"] == 3
    assert rep["num_hosts"] == 4

    # the mesh plane is BY CONSTRUCTION the ensemble pytree (mesh.py
    # init_mesh_state), so its pricing is the same exactness claim
    msh = init_mesh_state(cfg, model, MeshPlan(replicas=2, shards=2, rows=1))
    rep = memtrack.price_state(msh, cfg)
    assert rep["total_bytes"] == _manual_nbytes(msh)
    assert rep["replicas"] == 2


def test_price_state_abstract_equals_concrete():
    """`shadow-tpu mem` prices under jax.eval_shape: the abstract pytree
    must price byte-identical to the allocated one."""
    cfg, model, _tables, _st0 = _phold_world(num_hosts=4)
    concrete = init_state(cfg, model.init())
    abstract = jax.eval_shape(lambda: init_state(cfg, model.init()))
    assert (
        memtrack.price_state(abstract)["total_bytes"]
        == memtrack.price_state(concrete)["total_bytes"]
        == _manual_nbytes(concrete)
    )


def test_price_regrow_matches_grow_state():
    cfg, _model, _tables, st0 = _phold_world(num_hosts=4)
    q2, ob2 = cfg.queue_capacity * 2, 16
    projected = memtrack.price_regrow(st0, queue_capacity=q2,
                                      outbox_capacity=ob2)
    grown = grow_state(st0, queue_capacity=q2, outbox_capacity=ob2)
    assert projected == _manual_nbytes(grown)
    assert projected > tree_nbytes(st0)
    # a no-op regrow projects the current total
    assert memtrack.price_regrow(st0) == tree_nbytes(st0)


def test_max_hosts_for_budget_monotone():
    cfg, _model, _tables, st0 = _phold_world()
    report = memtrack.price_state(st0, cfg)
    budgets = [2**20, 2**24, 2**28, 2**32]
    fits = [memtrack.max_hosts_for_budget(report, b) for b in budgets]
    assert fits == sorted(fits)
    assert fits[-1] > fits[0] > 0
    assert memtrack.max_hosts_for_budget(report, 0) == 0


def test_render_report_table():
    cfg, _model, _tables, st0 = _phold_world()
    report = memtrack.price_state(st0, cfg)
    text = memtrack.render_report(report, hbm_gb=16)
    assert "dominant grid:" in text
    assert "queue" in text and "outbox" in text
    assert fmt_bytes(report["total_bytes"]) in text
    assert "16 GiB" in text  # the projection line


def test_leaf_nbytes_prices_key_leaves():
    key = jax.random.key(0)
    assert leaf_nbytes(key) == jax.random.key_data(key).nbytes
    abstract = jax.eval_shape(lambda: jax.random.key(0))
    assert leaf_nbytes(abstract) == leaf_nbytes(key)


# ---- live sampling: zero syncs, backend-tolerant ------------------------


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def _probe(**kw):
    import dataclasses

    from shadow_tpu.engine.round import ChunkProbe

    fields = {f.name: 0 for f in dataclasses.fields(ChunkProbe)}
    fields.update(kw)
    return ChunkProbe(**fields)


def test_device_memory_sampling_zero_fetches_and_fields(monkeypatch):
    """With a backend that reports memory_stats, every sample carries
    bytes_in_use summed across devices and peak maxed per device — and
    the sampling path performs not one jax.device_get."""
    from shadow_tpu.runtime.flightrec import FlightRecorder

    fetches = {"n": 0}
    real = jax.device_get

    def counting(x):
        fetches["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    monkeypatch.setattr(
        jax, "local_devices",
        lambda: [
            _FakeDevice({"bytes_in_use": 100, "peak_bytes_in_use": 300,
                         "bytes_limit": 1000}),
            _FakeDevice({"bytes_in_use": 50, "peak_bytes_in_use": 700,
                         "bytes_limit": 1000}),
        ],
    )
    rec = FlightRecorder(num_hosts=8)
    for i in range(3):
        sample = rec.observe(_probe(now=(i + 1) * 1000))
    assert sample["device_bytes_in_use"] == 150  # summed
    assert sample["device_peak_bytes"] == 700  # maxed
    assert fetches["n"] == 0
    # memtrack's aggregate view sums/maxes the same way
    dm = memtrack.device_memory(devices=jax.local_devices())
    assert dm["bytes_in_use"] == 150
    assert dm["peak_bytes_in_use"] == 700
    assert dm["bytes_limit"] == 2000


def test_device_memory_sampling_disables_on_cpu(monkeypatch):
    """A backend whose devices report no memory_stats (CPU returns None)
    disables sampling after ONE probe: samples carry no device fields
    and the device list is resolved exactly once."""
    from shadow_tpu.runtime.flightrec import FlightRecorder

    calls = {"n": 0}

    def tracked():
        calls["n"] += 1
        return [_FakeDevice(None)]

    monkeypatch.setattr(jax, "local_devices", tracked)
    rec = FlightRecorder(num_hosts=8)
    for i in range(3):
        sample = rec.observe(_probe(now=(i + 1) * 1000))
    assert "device_bytes_in_use" not in sample
    assert calls["n"] == 1
    assert memtrack.device_memory(devices=[_FakeDevice(None)]) is None


def test_write_prom_carries_device_gauges(tmp_path, monkeypatch):
    from shadow_tpu.runtime.flightrec import FlightRecorder

    monkeypatch.setattr(
        jax, "local_devices",
        lambda: [_FakeDevice({"bytes_in_use": 42, "peak_bytes_in_use": 99})],
    )
    rec = FlightRecorder(num_hosts=8)
    rec.observe(_probe(now=1000))
    pp = tmp_path / "m.prom"
    assert rec.write_prom(path=str(pp)) == str(pp)
    prom = pp.read_text()
    assert "shadow_tpu_device_bytes_in_use 42" in prom
    assert "shadow_tpu_device_peak_bytes 99" in prom


# ---- priced failures ----------------------------------------------------


def test_capacity_error_carries_priced_bytes():
    from shadow_tpu.engine.round import CapacityError, attach_capacity_bytes

    _cfg, _model, _tables, st0 = _phold_world(num_hosts=4)
    err = CapacityError("saturated")
    err.queue_overflow, err.outbox_overflow = 3, 0
    attach_capacity_bytes(err, st0)
    assert err.bytes_current > 0
    # only the queue was saturated: its x2 regrow doubles the capacity-
    # axis grids but not the per-host counters, so strictly between 1x
    # and 2x
    assert err.bytes_current < err.bytes_regrown < 2 * err.bytes_current
    assert "saturated buffer bytes" in str(err)
    assert fmt_bytes(err.bytes_current) in str(err)


def test_capacity_recovery_record_carries_priced_bytes():
    """The rollback-and-regrow record prices the full state before and
    after the double it applied — the headroom figures sim-stats and the
    recovery log line publish. Reuses the queue_capacity=2 world
    test_robustness compiles."""
    from shadow_tpu.runtime.recovery import (
        RecoveryPolicy,
        run_until_recovering,
    )

    cfg, model, tables, st0 = _phold_world(queue_capacity=2)
    _final, recoveries = run_until_recovering(
        st0, 60 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4,
        policy=RecoveryPolicy(max_recoveries=4, snapshot_interval_chunks=2),
    )
    assert recoveries
    rec = recoveries[0]
    assert rec["kind"] == "capacity"
    assert rec["bytes_current"] > 0
    assert rec["bytes_regrown"] > rec["bytes_current"]
    # the projection priced BEFORE growing matches the regrown shapes:
    # recompute it from a fresh world of the same seed capacity
    projected = memtrack.price_regrow(
        st0,
        queue_capacity=rec["queue_capacity"],
        outbox_capacity=rec["outbox_capacity"],
    )
    assert rec["bytes_regrown"] == projected


# ---- CLI + sim-stats surfaces -------------------------------------------

CONFIG = """
general:
  stop_time: 60 ms
  seed: 1
  data_directory: {data_dir}
  heartbeat_interval: null
  tracker: true
network:
  graph:
    type: 1_gbit_switch
experimental:
  rounds_per_chunk: 4
hosts:
  peer:
    network_node_id: 0
    # 12 hosts matches test_metrics_cli / test_checkpoint_cli exactly,
    # so the run-backed smoke below reuses their compiled chunk program
    # from the process-wide jit cache
    quantity: 12
    processes:
      - path: phold
        args:
          min_delay: "2 ms"
          max_delay: "12 ms"
"""


def _write(tmp_path) -> pathlib.Path:
    d = tmp_path / "mem"
    d.mkdir()
    cfg = d / "shadow.yaml"
    cfg.write_text(CONFIG.format(data_dir=d / "data"))
    return cfg


def test_cli_mem_prices_without_compiling(tmp_path, capsys):
    """`shadow-tpu mem` prints the table (dominant grid line included)
    and the --json report's total matches the exact leaf pricing of the
    state the run would allocate."""
    from shadow_tpu.cli import main as cli_main

    cfg_path = _write(tmp_path)
    assert cli_main(["mem", str(cfg_path), "--hbm-gb", "16"]) == 0
    out = capsys.readouterr().out
    assert "memory: 12 hosts" in out
    assert "dominant grid:" in out
    assert "hosts fit in 16 GiB HBM" in out

    assert cli_main(["mem", str(cfg_path), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["num_hosts"] == 12
    assert report["total_bytes"] == sum(
        g["bytes"] for g in report["groups"].values()
    )
    # the ensemble plane prices [R] rows of the same world
    assert cli_main(["mem", str(cfg_path), "--replicas", "3", "--json"]) == 0
    rep3 = json.loads(capsys.readouterr().out)
    assert rep3["replicas"] == 3
    assert rep3["total_bytes"] > report["total_bytes"]

    # user mistakes stay one-line errors, never tracebacks
    assert cli_main(["mem", str(tmp_path / "nope.yaml")]) == 1
    assert "shadow-tpu: error:" in capsys.readouterr().err


def test_sim_stats_carries_memory_section(tmp_path):
    """A completed run's sim-stats.json prices its final state: the
    memory block's total is the exact leaf pricing, grouped by
    subsystem, with the dominant grid named."""
    from shadow_tpu.runtime.cli_run import run_from_config

    cfg_path = _write(tmp_path)
    assert run_from_config(str(cfg_path)) == 0
    stats = json.loads(
        (tmp_path / "mem" / "data" / "sim-stats.json").read_text()
    )
    mem = stats["memory"]
    assert mem["num_hosts"] == 12
    assert mem["total_bytes"] == sum(mem["groups"].values())
    assert mem["dominant"]["name"].startswith("queue.")
    assert mem["bytes_per_host"] > 0
