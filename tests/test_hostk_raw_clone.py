"""Raw clone(CLONE_THREAD) adoption (round-2 verdict item 4; reference
ManagedThread::native_clone, managed_thread.rs:294-365 + the shim child
trampoline, shim_syscall.c:25-112): a guest that creates threads the
musl/Go way — raw clone with a self-managed stack, zero glibc pthread
involvement — gets its child adopted into the simulation: the child's
raw syscalls are simulated, scheduled deterministically, and its exit is
a kernel-visible THREAD_EXIT."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def rc_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("rc") / "raw_clone_guest"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(GUESTS / "raw_clone_guest.c")], check=True
    )
    return str(out)


def _run(tmp_path, rc_bin, sub="s"):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(
        tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / sub
    )
    p = k.add_process(ProcessSpec(host="box", args=[rc_bin]))
    try:
        k.run(10 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, p


def test_raw_clone_thread_adopted(tmp_path, rc_bin):
    k, p = _run(tmp_path, rc_bin)
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "cloned tid>0: 1" in out
    assert "child ran" in out
    assert "sum 42" in out
    assert "raw clone all ok" in out
    # the child's life was simulated: its nanosleep advanced sim time and
    # its syscalls hit the kernel
    names = [s for _, s, _ in p.syscall_log]
    assert names.count("nanosleep") >= 1


@pytest.fixture(scope="module")
def churn_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("rc") / "raw_clone_churn"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(GUESTS / "raw_clone_churn.c")], check=True
    )
    return str(out)


def test_raw_clone_slot_reuse(tmp_path, churn_bin):
    """ADVICE r3 (medium): exited raw-thread slots must be reusable; 140
    sequential create/join cycles exceed the 128-slot table."""
    k, p = _run(tmp_path, churn_bin)
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "churn ok 140" in out


def test_raw_clone_deterministic(tmp_path, rc_bin):
    a = _run(tmp_path, rc_bin, "r1")[1]
    b = _run(tmp_path, rc_bin, "r2")[1]
    assert a.stdout() == b.stdout()
    assert [s for _, s, _ in a.syscall_log] == [s for _, s, _ in b.syscall_log]
