"""The native C baseline (tools/native_baseline/tgen_pdes.c) must compute
the *same simulation* as the Python scalar oracle (cpu_ref/tgen_ref.py) —
same threefry draws, same TCP/shaping integer arithmetic, same window
loop — so the published baseline rate (BENCH vs_baseline denominator) is
provably measuring identical semantics at native speed, not a lighter
workload (round-3 verdict Missing #3)."""

import json
import pathlib
import subprocess

import pytest

from shadow_tpu.cpu_ref.tgen_ref import CpuRefTgen
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

from tests.test_cpu_ref_tgen import _world

REPO = pathlib.Path(__file__).resolve().parent.parent
NB = REPO / "tools" / "native_baseline"


@pytest.fixture(scope="module")
def nb_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("nb") / "tgen_pdes"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(NB / "tgen_pdes.c"), "-lm"], check=True
    )
    return out


def _run_c(nb_bin, tmp_path, tables, num_hosts, end_ns, seed, resp, pause,
           runahead, refill):
    import sys

    sys.path.insert(0, str(NB))
    from run_native_baseline import write_tables

    tp = tmp_path / "tables.bin"
    write_tables(tp, tables)
    r = subprocess.run(
        [str(nb_bin), str(tp), str(num_hosts), str(end_ns), str(seed),
         str(resp), str(pause), str(runahead), str(refill), str(refill)],
        check=True, capture_output=True, text=True,
    )
    return json.loads(r.stdout)


def test_native_baseline_matches_python_oracle(nb_bin, tmp_path):
    """Counter-for-counter identity with CpuRefTgen on the lossy+shaped
    configuration (loss draws, CoDel, token buckets, retransmits all in
    play)."""
    cfg, model, tables, host_node, bw = _world(8, 0.02, True, seed=13)
    end = 400 * NS_PER_MS

    ref = CpuRefTgen(cfg, model, tables, host_node,
                     tx_bytes_per_interval=bw, rx_bytes_per_interval=bw)
    ref.bootstrap()
    ref.run_until(end)

    c = _run_c(nb_bin, tmp_path, tables, 8, end, cfg.seed,
               model.resp_bytes, model.pause_ns, cfg.runahead_ns, bw)

    assert c["events"] == sum(ref.events_handled)
    assert c["packets_sent"] == sum(ref.packets_sent)
    assert c["packets_dropped"] == sum(ref.packets_dropped)
    assert c["codel_dropped"] == sum(ref.codel_dropped)
    assert c["streams_started"] == sum(ref.streams_started)
    assert c["streams_done"] == sum(ref.streams_done)
    assert c["bytes_down"] == sum(ref.bytes_down)
    assert c["resets"] == sum(ref.resets)
    assert c["bytes_sent"] == sum(ref.bytes_sent)
    assert c["bytes_recv"] == sum(ref.bytes_recv)
    assert c["retransmits"] == sum(
        s.retransmits for row in ref.slots for s in row
    )


def test_native_baseline_bench_topology_smoke(nb_bin, tmp_path):
    """The bench-shaped world (32-node lossy graph, 100 Mbit shaping)
    completes and reports a plausible native rate."""
    import bench

    cfg, model, tables, _st = bench._build(64)
    c = _run_c(nb_bin, tmp_path, tables, 64, int(0.1 * NS_PER_SEC), cfg.seed,
               model.resp_bytes, model.pause_ns, cfg.runahead_ns,
               bw_bits_per_sec_to_refill(100_000_000))
    assert c["streams_done"] == 32  # one stream per client in 100 ms
    assert c["bytes_down"] == 32 * model.resp_bytes
    assert c["rate"] > 1.0
