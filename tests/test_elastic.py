"""Elastic mesh (ISSUE 15): device-loss tolerance and grid-portable
checkpoints — the engine/unit tier.

Contracts pinned here:

  * `MeshPlan.degraded` walks the documented rung order (R×S → R×S/2 →
    1×S → single device), honors the surviving-device count and the
    host-axis divisibility, and terminates at None;
  * an injected `device-loss` fault mid-mesh-run degrades the grid and
    replays leaf-exact vs the fault-free run (modulo the established
    per-shard iteration diagnostics), with the reshape journaled as a
    kind="device-loss" recovery record;
  * real XLA runtime errors translate to DeviceLossError
    (device_loss_from); driver-control and plain errors do not;
  * outside the mesh plane a device loss is terminal but structured;
  * CapacityError's (replica, shard) naming and the whole-batch regrow
    stay correct on degenerate grids REACHED VIA DEGRADATION, not just
    grids requested up front (the satellite pin);
  * the sweep retry backoff is exponential with seeded, bounded jitter
    (deterministic replay, no lockstep stampede);
  * fingerprint portability: `general.mesh` is layout metadata — grids
    hash alike, replica-count changes refuse naming the key.
"""

import dataclasses
import pathlib

import jax
import numpy as np
import pytest

from test_pipeline import _phold_world

from shadow_tpu.engine.mesh import MeshPlan, init_mesh_state, run_mesh_until
from shadow_tpu.engine.round import (
    CapacityError,
    DeviceLossError,
    WatchdogExpired,
    device_loss_from,
)
from shadow_tpu.engine.state import state_to_host
from shadow_tpu.runtime import chaos
from shadow_tpu.runtime.mesh import MeshRunner
from shadow_tpu.runtime.recovery import RecoveryPolicy
from shadow_tpu.simtime import NS_PER_MS


def _assert_batch_exact(a, b, what=""):
    """Leaf-exact modulo the two established sharded-execution
    deviations (tests/test_mesh.py): per-shard iteration diagnostics
    and dead-slot queue garbage (live queue content is compared in
    canonical pop order via the host snapshot)."""
    from test_mesh import _canon_queue

    ha, hb = state_to_host(a), state_to_host(b)
    grid_leaves = (".queue.time", ".queue.tie", ".queue.kind",
                   ".queue.data", ".queue.aux")
    fa = jax.tree_util.tree_leaves_with_path(ha)
    fb = jax.tree_util.tree_leaves_with_path(hb)
    assert len(fa) == len(fb)
    for (path, la), (_, lb) in zip(fa, fb):
        ks = jax.tree_util.keystr(path)
        if ("iters_done" in ks or "lanes_live" in ks or "exch_hwm" in ks
                or ks in grid_leaves):
            continue
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"mismatch{what} at {ks}"
        )
    for r in range(a.now.shape[0]):
        qa = jax.tree.map(lambda l: l[r], a.queue)
        qb = jax.tree.map(lambda l: l[r], b.queue)
        for h in range(qa.num_hosts):
            assert _canon_queue(qa, h) == _canon_queue(qb, h), (
                f"queue content mismatch{what} at replica {r} host {h}"
            )


# --- degradation ladder units -------------------------------------------


def test_mesh_degradation_ladder_order():
    plan = MeshPlan(replicas=2, shards=4, rows=2)
    # lose one of 8 devices: halve the shard axis first
    nxt = plan.degraded(7, 8)
    assert (nxt.rows, nxt.shards) == (2, 2)
    # walk all the way down: 2x2 -> 2x1 -> 1x1 -> terminal
    nxt2 = nxt.degraded(7, 8)
    assert (nxt2.rows, nxt2.shards) == (2, 1)
    nxt3 = nxt2.degraded(7, 8)
    assert (nxt3.rows, nxt3.shards) == (1, 1)
    assert nxt3.local_replicas == 2  # both worlds vmapped on one device
    assert nxt3.degraded(8, 8) is None  # nothing below single device


def test_mesh_degradation_honors_survivors_and_divisibility():
    plan = MeshPlan(replicas=2, shards=4, rows=2)
    # only 3 survivors: R×S/2 (4 devices) and 1×S (4) don't fit — 1×2 does
    nxt = plan.degraded(3, 8)
    assert (nxt.rows, nxt.shards) == (1, 2)
    # an odd shard axis halves to 1 (integer rung), keeping the rows
    plan6 = MeshPlan(replicas=2, shards=3, rows=2)
    nxt6 = plan6.degraded(5, 6)
    assert (nxt6.rows, nxt6.shards) == (2, 1)
    # a rung must SHED devices, never rearrange: 1x1 from 1x1 is None
    assert MeshPlan(replicas=4, shards=1, rows=1).degraded(8, 8) is None


# --- DeviceLossError translation ----------------------------------------


def test_device_loss_from_translates_xla_runtime_errors():
    from jaxlib.xla_extension import XlaRuntimeError

    err = XlaRuntimeError("INTERNAL: device failed")
    loss = device_loss_from(err, 5)
    assert isinstance(loss, DeviceLossError)
    assert loss.chunk == 5 and not loss.injected
    assert device_loss_from(
        XlaRuntimeError("UNAVAILABLE: client disconnected"), 2
    ) is not None
    # non-loss XLA statuses must NOT degrade the grid (the allowlist):
    # OOM on fewer devices is worse, and deterministic errors would
    # just replay into themselves down the whole ladder
    for status in ("RESOURCE_EXHAUSTED: out of memory",
                   "INVALID_ARGUMENT: shape mismatch",
                   "FAILED_PRECONDITION: donated buffer",
                   "DEADLINE_EXCEEDED: collective timeout"):
        assert device_loss_from(XlaRuntimeError(status), 1) is None
    # driver-control and plain errors pass through untouched
    assert device_loss_from(WatchdogExpired(1, 0.5), 1) is None
    assert device_loss_from(RuntimeError("Array has been deleted"), 1) is None
    assert device_loss_from(ValueError("shape"), 1) is None
    # an already-typed loss is returned as itself
    pre = DeviceLossError(2, device_id=3)
    assert device_loss_from(pre, 9) is pre


# --- injected device loss: degrade + leaf-exact replay ------------------


def test_device_loss_degrades_mesh_and_replays_leaf_exact():
    """The tentpole pin: an injected device-loss mid-batch completes on
    a degraded grid with results leaf-exact vs fault-free, the reshape
    recorded as a kind="device-loss" recovery record naming both
    grids."""
    assert jax.device_count() == 8
    cfg, model, tables, _ = _phold_world(num_hosts=8)
    end = 40 * NS_PER_MS
    plan = MeshPlan(replicas=2, shards=4, rows=2)
    ref = run_mesh_until(
        init_mesh_state(cfg, model, plan, 1), end, model, tables, cfg, plan,
        rounds_per_chunk=4,
    )

    runner = MeshRunner(model, tables, cfg, plan=plan, rounds_per_chunk=4)
    fault = chaos.FaultPlan(
        seed=0, faults=[{"kind": "device-loss", "at": 2, "target": "3"}]
    )
    with chaos.installed(fault):
        final = runner.run(
            end,
            recovery=RecoveryPolicy(max_recoveries=4,
                                    snapshot_interval_chunks=2),
        )
    assert runner.plan.devices_needed < plan.devices_needed
    assert runner.mesh_degradations, "the reshape must be journaled"
    d = runner.mesh_degradations[0]
    assert d["grid_from"] == "2x4" and d["device"] == 3
    rec = runner.recovery_report[0]
    assert rec["kind"] == "device-loss" and rec["injected"]
    assert rec["grid_from"] == "2x4" and rec["grid_to"] == d["grid_to"]
    assert rec["device"] == 3 and "replay_from_ns" in rec
    # the degraded grid genuinely avoids the lost device
    assert all(
        dev.id != 3 for dev in np.asarray(runner._get_mesh().devices).ravel()
    )
    _assert_batch_exact(final, ref, " (device-loss replay)")


def test_device_loss_terminal_outside_mesh_is_structured():
    """No second device to degrade onto: the pure-ensemble runner's
    device loss is terminal, typed, and carries its (empty) recovery
    history instead of hanging or mutating results."""
    from shadow_tpu.runtime.ensemble import EnsembleRunner

    cfg, model, tables, _ = _phold_world(num_hosts=8)
    runner = EnsembleRunner(model, tables, cfg, num_replicas=2,
                            rounds_per_chunk=4)
    fault = chaos.FaultPlan(
        seed=0, faults=[{"kind": "device-loss", "at": 1}]
    )
    with chaos.installed(fault):
        with pytest.raises(DeviceLossError, match="lost a device at chunk 1"):
            runner.run(
                40 * NS_PER_MS,
                recovery=RecoveryPolicy(max_recoveries=4,
                                        snapshot_interval_chunks=2),
            )
    # losing a device the run does NOT occupy cannot touch it: a fault
    # targeting an idle device never fires (the launch seam advertises
    # only the state's own devices), so the single-device run completes
    idle = str(max(d.id for d in jax.devices()))
    fault2 = chaos.FaultPlan(
        seed=0, faults=[{"kind": "device-loss", "at": 1, "target": idle}]
    )
    with chaos.installed(fault2):
        runner.run(
            40 * NS_PER_MS,
            recovery=RecoveryPolicy(max_recoveries=4,
                                    snapshot_interval_chunks=2),
        )
    assert not fault2.fired, "an idle device's loss must not fire"


# --- satellite: degenerate grids reached via degradation ----------------


def test_capacity_naming_on_grid_reached_via_degradation():
    """(replica, shard) naming must stay correct on a grid the run
    DEGRADED onto, not just one requested up front: after a device loss
    burns the only recovery rung, the real overflow's terminal
    CapacityError names coordinates within the degraded grid."""
    cfg, model, tables, _ = _phold_world(num_hosts=8, queue_capacity=2)
    cfg = dataclasses.replace(cfg, outbox_capacity=1)
    plan = MeshPlan(replicas=2, shards=4, rows=2)
    runner = MeshRunner(model, tables, cfg, plan=plan, rounds_per_chunk=4)
    fault = chaos.FaultPlan(
        seed=0, faults=[{"kind": "device-loss", "at": 0, "target": "7"}]
    )
    with chaos.installed(fault):
        with pytest.raises(CapacityError, match=r"\(replica \d, shard \d\)") as ei:
            runner.run(
                40 * NS_PER_MS,
                recovery=RecoveryPolicy(max_recoveries=1,
                                        snapshot_interval_chunks=2),
            )
    err = ei.value
    degraded_shards = runner.plan.shards
    assert degraded_shards < 4  # the loss really degraded the grid first
    assert err.replica is not None and 0 <= err.replica < 2
    assert err.shard is not None and 0 <= err.shard < degraded_shards
    assert err.mesh_cells and all(
        c["shard"] < degraded_shards for c in err.mesh_cells
    )
    # the terminal error still carries the device-loss degradation it
    # survived before dying (visibly-degraded contract)
    assert [r["kind"] for r in err.recoveries] == ["device-loss"]


def test_whole_batch_regrow_on_grid_reached_via_degradation():
    """Rollback-and-regrow after the grid degraded: the regrown replay
    on the smaller grid is leaf-exact vs a fault-free run that started
    at the grown capacity."""
    cfg_small, model, tables, _ = _phold_world(num_hosts=8, queue_capacity=2)
    end = 60 * NS_PER_MS
    plan = MeshPlan(replicas=2, shards=2, rows=1)
    runner = MeshRunner(
        model, tables, cfg_small, plan=plan, rounds_per_chunk=4
    )
    fault = chaos.FaultPlan(
        seed=0, faults=[{"kind": "device-loss", "at": 0, "target": "1"}]
    )
    with chaos.installed(fault):
        final = runner.run(
            end,
            recovery=RecoveryPolicy(max_recoveries=5,
                                    snapshot_interval_chunks=2),
        )
    kinds = [r["kind"] for r in runner.recovery_report]
    assert kinds[0] == "device-loss" and "capacity" in kinds
    grown_cap = next(
        r["queue_capacity"] for r in reversed(runner.recovery_report)
        if r["kind"] == "capacity"
    )
    assert grown_cap > cfg_small.queue_capacity
    assert runner.plan.devices_needed < plan.devices_needed

    cfg_big = dataclasses.replace(cfg_small, queue_capacity=grown_cap)
    ens_big = run_mesh_until(
        init_mesh_state(cfg_big, model, plan, 1),
        end, model, tables, cfg_big, plan, rounds_per_chunk=4,
    )
    _assert_batch_exact(final, ens_big, " (regrow on degraded grid)")


# --- satellite: seeded retry backoff jitter -----------------------------


def test_retry_backoff_seeded_bounded_jitter():
    from shadow_tpu.runtime.sweep import retry_backoff_s

    # deterministic: same (job, attempt) -> identical value, replay-safe
    assert retry_backoff_s(1.0, "t.ph-s3", 1) == retry_backoff_s(
        1.0, "t.ph-s3", 1
    )
    # bounded: jitter factor in [0.5, 1.5) around the exponential base
    for attempt in (1, 2, 3):
        base = 1.0 * 2 ** (attempt - 1)
        v = retry_backoff_s(1.0, "t.ph-s3", attempt)
        assert base * 0.5 <= v < base * 1.5
    # de-lockstepped: split siblings retry at different walls
    vals = {round(retry_backoff_s(1.0, f"t.ph-s{i}", 1), 6) for i in range(8)}
    assert len(vals) == 8
    # zero base stays zero (backoff disabled)
    assert retry_backoff_s(0.0, "t.ph-s3", 2) == 0.0


# --- grid-portable fingerprints + refusal UX ----------------------------


_CFG = """
general:
  stop_time: 1 s
  seed: {seed}
  {extra}
hosts:
  peer:
    network_node_id: 0
    quantity: 8
    processes:
      - path: phold
        args: {{min_delay: "2 ms", max_delay: "12 ms"}}
"""


def _cfg(seed=1, extra=""):
    from shadow_tpu.config import load_config_str

    return load_config_str(_CFG.format(seed=seed, extra=extra))


def test_fingerprint_mesh_is_layout_metadata():
    from shadow_tpu.config.fingerprint import config_fingerprint

    on_2x4 = config_fingerprint(_cfg(extra="mesh: 2x4"))
    # the same two worlds on any layout hash alike...
    assert on_2x4 == config_fingerprint(
        _cfg(extra="replicas: 2\n  mesh: 1x2")
    )
    assert on_2x4 == config_fingerprint(_cfg(extra="replicas: 2"))
    # ...but changing the number of simulated worlds still refuses
    assert on_2x4 != config_fingerprint(_cfg(extra="replicas: 3"))
    assert on_2x4 != config_fingerprint(_cfg(extra="mesh: 4x2"))  # R=4


def test_checkpoint_mismatch_names_keys_and_grids(tmp_path):
    """The resume-refusal UX satellite: a genuine world mismatch names
    the offending keys and both grids, never two opaque hashes; a
    grid-only difference is not a mismatch at all."""
    from shadow_tpu.config.fingerprint import (
        config_fingerprint,
        fingerprint_dict,
    )
    from shadow_tpu.runtime.checkpoint import (
        CheckpointError,
        CheckpointManager,
        load_checkpoint,
    )

    cfg, model, tables, st = _phold_world(num_hosts=8)
    host = state_to_host(st)
    saved_cfg = _cfg(seed=1, extra="mesh: 2x4")
    ckpt = CheckpointManager(
        str(tmp_path), 0, config_fingerprint(saved_cfg),
        layout="2x4", detail=fingerprint_dict(saved_cfg),
    )
    path = ckpt.write(host, final=True)

    # same world, different grid: loads fine (layout is metadata)
    other_grid = _cfg(seed=1, extra="replicas: 2\n  mesh: 1x2")
    load_checkpoint(
        path, st, config_fingerprint(other_grid),
        detail=fingerprint_dict(other_grid), layout="1x2",
    )

    # different world: refusal names the key and both grids
    bad = _cfg(seed=2, extra="replicas: 2\n  mesh: 1x2")
    with pytest.raises(CheckpointError) as ei:
        load_checkpoint(
            path, st, config_fingerprint(bad),
            detail=fingerprint_dict(bad), layout="1x2",
        )
    msg = str(ei.value)
    assert "general.seed: 1 != 2" in msg
    assert "grid 2x4" in msg and "grid 1x2" in msg
    assert "…" not in msg  # named keys, not truncated hashes


# --- service wiring: a device-lossy sweep batch finishes degraded -------


def test_sweep_batch_survives_device_loss(tmp_path):
    """Acceptance (service wiring): a mesh sweep batch that hits device
    loss finishes on the degraded grid instead of quarantining — every
    job done, the reshape in the batch's manifest record."""
    import json

    from shadow_tpu.runtime.cli_run import run_sweep

    base = tmp_path / "base.yaml"
    base.write_text(
        """
general:
  stop_time: 60 ms
  heartbeat_interval: null
network:
  graph:
    type: 1_gbit_switch
experimental:
  rounds_per_chunk: 4
chaos:
  faults:
    - kind: device-loss
      at: 1
      target: "1"
hosts:
  peer:
    network_node_id: 0
    quantity: 8
    processes:
      - path: phold
        args:
          min_delay: "2 ms"
          max_delay: "12 ms"
"""
    )
    out = tmp_path / "out"
    spec = tmp_path / "sweep.yaml"
    spec.write_text(
        f"""
sweep:
  base: base.yaml
  output_dir: {out}
  capacity: 2
  mesh: 2x2
  jobs:
    - name: ph
      seed_range: [0, 2]
"""
    )
    assert run_sweep(str(spec)) == 0, "the batch must finish, not quarantine"
    m = json.loads((out / "sweep-manifest.json").read_text())
    assert m["jobs_done"] == 2
    assert m["jobs_failed"] == 0 and m["jobs_quarantined"] == 0
    b = m["batches"][0]
    assert b["status"] == "done"
    assert b["recoveries"] >= 1
    assert b["mesh_effective"] != "2x2"
    assert b["mesh_degradations"][0]["grid_from"] == "2x2"
    # both jobs published standalone-shaped stats
    for job in m["jobs"]:
        assert job["status"] == "done"
        stats = json.loads(
            (pathlib.Path(job["data_directory"]) / "sim-stats.json").read_text()
        )
        assert stats["events_handled"] > 0
