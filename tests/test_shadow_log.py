"""Async ShadowLogger (reference: shadow_logger.rs — queue + dedicated
flush thread + panic flush)."""

import io

from shadow_tpu.utils import shadow_log


def test_async_records_flush_in_order():
    buf = io.StringIO()
    shadow_log.set_sink(buf)
    try:
        for i in range(200):
            shadow_log.slog("info", i * 1000, "host", f"record-{i}")
        shadow_log.flush()
        lines = buf.getvalue().splitlines()
        assert len(lines) == 200
        assert [ln.rsplit(" ", 1)[-1] for ln in lines] == [
            f"record-{i}" for i in range(200)
        ]
        assert "[2000-01-01 00:00:00.000000000]" in lines[0]
    finally:
        shadow_log.set_sink(None)


def test_error_records_flush_immediately():
    buf = io.StringIO()
    shadow_log.set_sink(buf)
    try:
        shadow_log.slog("error", 0, "host", "boom")
        # no explicit flush: error level drains synchronously
        assert "boom" in buf.getvalue()
    finally:
        shadow_log.set_sink(None)
