"""The scalar TCP oracle vs the device engine on the *flagship* tgen
workload (the exact model bench.py measures): repeated request/response
streams with port recycling, slot reuse, loss, shaping + CoDel, TIMEWAIT
turnover. Two independent implementations of the same specification must
agree bit-for-bit — every TCP state field, every model counter, every
leftover queue entry (round-2 verdict item 3; reference analogue:
src/test/determinism/CMakeLists.txt:1-40)."""

import random

import numpy as np
import pytest

from shadow_tpu import equeue
from shadow_tpu.cpu_ref.tgen_ref import CpuRefTgen
from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, run_until
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.tgen import TgenModel
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS

from tests.test_cpu_ref_bulk import TCP_FIELDS


def _world(num_hosts, loss, shaped, seed):
    rng_py = random.Random(seed)
    n_nodes = 4
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "1 ms" ]')
    for i in range(n_nodes):
        for j in range(i + 1, n_nodes):
            lines.append(
                f'  edge [ source {i} target {j} latency "{rng_py.randrange(2, 6)} ms" packet_loss {loss} ]'
            )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    host_node = [i % n_nodes for i in range(num_hosts)]
    tables = compute_routing(graph, block=4).with_hosts(host_node)
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=128,
        outbox_capacity=16,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
        use_netstack=shaped,
    )
    model = TgenModel(
        num_hosts=num_hosts,
        num_clients=num_hosts // 2,
        num_servers=num_hosts - num_hosts // 2,
        resp_bytes=25_000,
        pause_ns=40 * NS_PER_MS,
    )
    bw = bw_bits_per_sec_to_refill(20_000_000) if shaped else None
    return cfg, model, tables, host_node, bw


@pytest.mark.parametrize(
    "loss,shaped,end_ms,lanes",
    [(0.0, False, 250, 0), (0.05, False, 400, 0), (0.02, True, 400, 0), (0.02, True, 400, 3)],
    ids=["clean", "lossy", "lossy-shaped", "lossy-shaped-compact"],
)
def test_device_tgen_matches_scalar_oracle(loss, shaped, end_ms, lanes):
    import dataclasses

    cfg, model, tables, host_node, bw = _world(8, loss, shaped, seed=13)
    if lanes:
        cfg = dataclasses.replace(cfg, active_lanes=lanes)
    end = end_ms * NS_PER_MS

    st = init_state(cfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw)
    st = bootstrap(st, model, cfg)
    st = run_until(st, end, model, tables, cfg, rounds_per_chunk=16)

    ref = CpuRefTgen(cfg, model, tables, host_node,
                     tx_bytes_per_interval=bw, rx_bytes_per_interval=bw)
    ref.bootstrap()
    ref.run_until(end)

    # every TCP state field, bit for bit
    for f in TCP_FIELDS:
        dev = np.asarray(getattr(st.model.tcp, f))
        np.testing.assert_array_equal(dev, ref.tcp_field(f).astype(dev.dtype), err_msg=f)

    # model + engine counters
    np.testing.assert_array_equal(np.asarray(st.model.streams_started), ref.streams_started)
    np.testing.assert_array_equal(np.asarray(st.model.streams_done), ref.streams_done)
    np.testing.assert_array_equal(np.asarray(st.model.bytes_down), ref.bytes_down)
    np.testing.assert_array_equal(np.asarray(st.model.resets), ref.resets)
    np.testing.assert_array_equal(np.asarray(st.seq), np.array(ref.seq, np.uint32))
    np.testing.assert_array_equal(np.asarray(st.rng_counter), np.array(ref.ctr, np.uint32))
    np.testing.assert_array_equal(np.asarray(st.packets_sent), ref.packets_sent)
    np.testing.assert_array_equal(np.asarray(st.packets_dropped), ref.packets_dropped)
    np.testing.assert_array_equal(np.asarray(st.events_handled), ref.events_handled)
    if shaped:
        np.testing.assert_array_equal(np.asarray(st.net.codel_dropped), ref.codel_dropped)
        np.testing.assert_array_equal(np.asarray(st.net.bytes_sent), ref.bytes_sent)
        np.testing.assert_array_equal(np.asarray(st.net.bytes_recv), ref.bytes_recv)

    # leftover queue contents in canonical order
    for h in range(cfg.num_hosts):
        assert equeue.debug_sorted_events(st.queue, h) == ref.queue_contents(h), f"host {h}"

    # the run actually cycled streams (oracle self-check)
    assert sum(ref.streams_done) > 0
    assert sum(ref.bytes_down) >= sum(ref.streams_done) * model.resp_bytes
