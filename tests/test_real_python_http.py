"""Stock CPython as an in-sim server: /usr/bin/python3 -m http.server
serving distro curl over the simulated network — the nginx-grade
acceptance workload for syscall breadth (round-3 verdict Next #3;
reference flagship example: examples/http-server nginx+curl,
src/test/examples/). CPython's startup walks the interpreter tree with
getdents64/newfstatat/statx, readlink, getcwd; the server loop runs
selectors (poll/epoll) over a listening socket; the resolver uses the
simulated DNS via the hostent family. Run-twice determinism covers the
whole transcript."""

import json
import os
import pathlib

import pytest

from shadow_tpu.runtime.cli_run import run_from_config

PY = "/usr/bin/python3"
CURL = "/usr/bin/curl"

pytestmark = pytest.mark.skipif(
    not (os.access(PY, os.X_OK) and os.access(CURL, os.X_OK)),
    reason="system python3/curl missing",
)

CONFIG = """
general:
  stop_time: 10 s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {py}
        args: ["-u", "-m", "http.server", "80", "--bind", "0.0.0.0"]
        expected_final_state: running
  client:
    network_node_id: 0
    processes:
      - path: {curl}
        args: ["-sS", "--max-time", "5", "-o", "page.html", "http://server/"]
        start_time: 3 s
"""


def _run(tmp_path, sub):
    d = tmp_path / sub
    d.mkdir(parents=True)
    cfg = d / "shadow.yaml"
    cfg.write_text(CONFIG.format(data_dir=d / "data", py=PY, curl=CURL))
    rc = run_from_config(str(cfg))
    return rc, d / "data"


def test_python_http_server_serves_curl(tmp_path):
    rc, data = _run(tmp_path, "a")
    assert rc == 0
    page = (data / "client" / "page.html").read_text()
    assert "Directory listing" in page
    stdout = next((data / "server").glob("python3.*.stdout")).read_text()
    assert "Serving HTTP on 11.0.0.1 port 80" in stdout
    # the GET is logged (to stderr) at *simulated* time by the stock logger
    stderr = next((data / "server").glob("python3.*.stderr")).read_text()
    assert '[01/Jan/2000 00:00:03] "GET / HTTP/1.1" 200' in stderr
    stats = json.loads((data / "sim-stats.json").read_text())
    assert sum(stats["syscall_counts"].values()) > 10_000  # real startup ran


def test_python_http_server_deterministic(tmp_path):
    outs = []
    for sub in ("r1", "r2"):
        rc, data = _run(tmp_path, sub)
        assert rc == 0
        page = (data / "client" / "page.html").read_bytes()
        stdout = next((data / "server").glob("python3.*.stdout")).read_bytes()
        stderr = next((data / "server").glob("python3.*.stderr")).read_bytes()
        outs.append((page, stdout, stderr))
    assert outs[0] == outs[1]
