"""Event-exchange v2 (engine/round.py _flush_segment + equeue.
push_many_segment): the dense-vs-segment equivalence matrix and the
compact-pool behavior pins.

Contracts pinned here:

  * exchange="segment" is trajectory- and stat-leaf-exact vs the dense
    landing family ("dense" == "all_to_all", state.py trace_static_cfg)
    on every registered model across every engine; queue grids compare
    as live content in canonical (time, tie) pop order — slot PLACEMENT
    is the one fact the segment landing lays out differently (free-slot
    rank order vs the dense [H, deliver_lanes] grid), and pop order is
    key-driven either way;
  * a bursty fan-in round that overflows a narrow dense deliver-lanes
    grid lands in full under the segment pool (the per-row capacity
    check replaces the per-lane one) and stays equal to a roomy dense
    landing;
  * pool_capacity truncation is LOUD (outbox overflow lane +
    CapacityError) and the error names the knob, the exchange-pool
    occupancy high-water, and the top destination hosts;
  * segment ensemble slices are leaf-exact vs standalone segment runs
    and pop-order-equal vs dense singles; the 2-D mesh plane runs the
    ppermute-ring segment exchange unpinned under its replica vmap
    (test_mesh pins the cfg seam; the slice equivalence lives here);
  * an injected chaos capacity fault under exchange="segment" takes the
    standard rollback-and-regrow path and recovers leaf-exact.

Quick tier: one dense-vs-segment phold smoke per engine plus the pure
pool/validation pins; the full model x engine matrix, the sharded /
ensemble / mesh cells, and the chaos pin run in the full tier
(tests/conftest.py SLOW_TESTS).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_mesh import _assert_mesh_slice_exact, _single_run
from test_overlay import _onion, _world as _overlay_world
from test_pipeline import _phold_world
from test_pump import _normalize

from shadow_tpu import equeue
from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.ensemble import (
    init_ensemble_state,
    replica_seeds,
    replica_slice,
    run_ensemble_until,
)
from shadow_tpu.engine.mesh import (
    MeshPlan,
    init_mesh_state,
    replica_slice as mesh_replica_slice,
    run_mesh_until,
)
from shadow_tpu.engine.round import (
    CapacityError,
    bootstrap,
    capacity_topk,
    check_capacity,
    flush_outbox,
    run_until,
)
from shadow_tpu.models.bulk import BulkTcpModel
from shadow_tpu.models.overlay import CdnModel, GossipModel
from shadow_tpu.models.phold import PholdModel
from shadow_tpu.models.tgen import TgenModel
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS

_ENGINES = [("plain", 0), ("pump", 3), ("megakernel", 3)]


def _run_mode(model, cfg, tables, bw, engine, k, mode, end):
    c = dataclasses.replace(cfg, engine=engine, pump_k=k, exchange=mode)
    st = init_state(
        c, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw
    )
    st = bootstrap(st, model, c)
    st = run_until(st, end, model, tables, c, rounds_per_chunk=8)
    check_capacity(st)
    return st


def _assert_pop_order_equal(a, b, what=""):
    """Dense-vs-segment equality: every leaf exact after the queue rows
    are canonicalized to (time, tie) pop order with tombstone payloads
    zeroed (test_pump._normalize — the established cross-engine idiom).
    Slot layout is the ONLY deviation the segment landing is allowed."""
    a, b = _normalize(a), _normalize(b)
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        ks = jax.tree_util.keystr(path)
        assert jnp.array_equal(la, lb), f"mismatch{what} at {ks}"


@pytest.mark.parametrize("engine,k", _ENGINES, ids=[e for e, _ in _ENGINES])
def test_segment_matches_dense_smoke(engine, k):
    """Quick-tier acceptance pin: exchange='segment' equals the dense
    landing on phold for every engine (the 'dense' alias is exercised on
    purpose — it must select the all_to_all trace)."""
    model = PholdModel(
        num_hosts=12, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
    )
    cfg, tables = _overlay_world(model, seed=5)
    end = 40 * NS_PER_MS
    dense = _run_mode(model, cfg, tables, None, engine, k, "dense", end)
    seg = _run_mode(model, cfg, tables, None, engine, k, "segment", end)
    assert int(dense.events_handled.sum()) > 0
    _assert_pop_order_equal(dense, seg, f" ({engine} dense vs segment)")


def _matrix_world(name):
    """One small world per registered model (the six names the
    acceptance matrix runs; registry.py _REGISTRY)."""
    if name == "phold":
        model = PholdModel(
            num_hosts=12, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
        )
        cfg, tables = _overlay_world(model, seed=5)
        return model, cfg, tables, None
    if name == "bulk-tcp":
        model = BulkTcpModel(num_hosts=12, num_pairs=3, total_bytes=20_000)
    elif name == "tgen":
        model = TgenModel(
            num_hosts=12, num_clients=6, num_servers=6, resp_bytes=20_000,
            pause_ns=30 * NS_PER_MS,
        )
    elif name == "onion":
        model = _onion()
    elif name == "cdn":
        model = CdnModel(num_hosts=12, num_mids=1, num_leaves=2, objects=32)
    elif name == "gossip":
        model = GossipModel(
            num_hosts=12, view_size=4, fanout=2, churn_ppm=100_000
        )
    else:  # pragma: no cover - parametrize list is closed
        raise AssertionError(name)
    cfg, tables = _overlay_world(model, seed=5)
    bw = None
    if name in ("bulk-tcp", "tgen"):
        cfg = dataclasses.replace(cfg, use_netstack=True, deliver_lanes=48)
        bw = bw_bits_per_sec_to_refill(50_000_000)
    return model, cfg, tables, bw


@pytest.mark.parametrize("engine,k", _ENGINES, ids=[e for e, _ in _ENGINES])
@pytest.mark.parametrize(
    "name", ["phold", "bulk-tcp", "tgen", "onion", "cdn", "gossip"]
)
def test_segment_matches_dense_matrix(name, engine, k):
    """The full acceptance matrix: all six registered models x all three
    engines, dense vs segment pop-order-exact."""
    model, cfg, tables, bw = _matrix_world(name)
    end = 150 * NS_PER_MS
    dense = _run_mode(model, cfg, tables, bw, engine, k, "dense", end)
    seg = _run_mode(model, cfg, tables, bw, engine, k, "segment", end)
    assert int(dense.events_handled.sum()) > 0
    assert int(dense.packets_sent.sum()) > 0  # exchange actually exercised
    _assert_pop_order_equal(
        dense, seg, f" ({name}/{engine} dense vs segment)"
    )


def _bursty_state(cfg, model, tables):
    """A deliberately bursty flush: every host stages 2 packets, ALL to
    host 0 — 16 deliveries into one row, more than a narrow dense
    deliver-lanes grid can land but well inside the queue row."""
    st = init_state(cfg, model.init())  # NO bootstrap: queue stays empty
    h, o = st.outbox.valid.shape
    valid = np.zeros((h, o), bool)
    valid[:, :2] = True
    time = np.full((h, o), (1 << 62) - 1, np.int64)
    tie = np.zeros((h, o), np.int64)
    for i in range(h):
        for j in range(2):
            time[i, j] = 10 * NS_PER_MS + i * 2 + j
            tie[i, j] = i * 2 + j + 1
    ob = st.outbox.replace(
        valid=jnp.asarray(valid),
        dst=jnp.zeros((h, o), jnp.int32),
        time=jnp.asarray(time),
        tie=jnp.asarray(tie),
        aux=jnp.where(jnp.asarray(valid), jnp.int32(100), jnp.int32(0)),
        fill=jnp.full((h,), 2, jnp.int32),
    )
    return st.replace(outbox=ob)


def test_bursty_fanin_overflows_lane_but_fits_pool():
    """The satellite pin: the same staged burst overflows a
    deliver_lanes=4 dense grid (loudly) but lands in full under the
    segment pool, equal to a roomy dense landing in pop order."""
    model = PholdModel(num_hosts=8)
    cfg, tables = _overlay_world(
        model, seed=3, queue_capacity=64, outbox_capacity=4
    )

    narrow = dataclasses.replace(cfg, deliver_lanes=4, exchange="dense")
    st_n = flush_outbox(_bursty_state(narrow, model, tables), None, narrow)
    assert int(st_n.queue.count[0]) == 4  # grid-bounded landing
    dropped = int(st_n.queue.overflow.sum()) + int(st_n.outbox.overflow.sum())
    assert dropped == 12
    with pytest.raises(CapacityError) as ei:
        check_capacity(st_n)
    assert "pool_capacity" in str(ei.value)  # the message names the knob
    topk = capacity_topk(st_n)
    assert topk.startswith("top destination hosts by landed events")
    assert "host 0" in topk

    seg = dataclasses.replace(cfg, deliver_lanes=4, exchange="segment")
    st_s = flush_outbox(_bursty_state(seg, model, tables), None, seg)
    check_capacity(st_s)  # no drops: capacity is per ROW, not per lane
    assert int(st_s.queue.count[0]) == 16
    assert int(st_s.queue.overflow.sum()) == 0

    roomy = dataclasses.replace(cfg, exchange="dense")  # full-width grid
    st_r = flush_outbox(_bursty_state(roomy, model, tables), None, roomy)
    for h in range(cfg.num_hosts):
        assert equeue.debug_sorted_events(
            st_s.queue, h
        ) == equeue.debug_sorted_events(st_r.queue, h), f"host {h}"


def test_pool_capacity_truncates_loudly():
    """pool_capacity below the round's traffic drops the tail into the
    outbox overflow lane and check_capacity reports the pool occupancy
    high-water plus the sizing advice — never a silent truncation."""
    model = PholdModel(num_hosts=8)
    cfg, tables = _overlay_world(
        model, seed=3, queue_capacity=64, outbox_capacity=4
    )
    small = dataclasses.replace(cfg, exchange="segment", pool_capacity=6)
    st = flush_outbox(_bursty_state(small, model, tables), None, small)
    assert int(st.queue.count[0]) == 6
    assert int(st.outbox.overflow.sum()) == 10
    # the occupancy high-water rides the tracker plane into the message
    st = st.replace(
        tracker=st.tracker.replace(
            exch_hwm=st.tracker.exch_hwm.at[0].set(jnp.int32(16))
        )
    )
    with pytest.raises(CapacityError) as ei:
        check_capacity(st)
    msg = str(ei.value)
    assert "exchange pool occupancy hwm=16 events/round" in msg
    assert "pool_capacity" in msg and "0 = whole outbox" in msg
    assert ei.value.exchange_hwm == 16
    assert ei.value.outbox_overflow == 10


def test_exchange_config_validation():
    with pytest.raises(ValueError, match="exchange"):
        EngineConfig(num_hosts=4, exchange="bogus")
    with pytest.raises(ValueError, match="pool_capacity"):
        EngineConfig(num_hosts=4, pool_capacity=-1)
    # "dense" is a pure alias of all_to_all: same compile-cache key
    from shadow_tpu.engine.state import trace_static_cfg

    a = trace_static_cfg(EngineConfig(num_hosts=4, exchange="dense"))
    b = trace_static_cfg(EngineConfig(num_hosts=4, exchange="all_to_all"))
    assert a == b
    s = trace_static_cfg(EngineConfig(num_hosts=4, exchange="segment"))
    assert s.exchange == "segment"  # distinct trace family


def test_ensemble_segment_slices_exact():
    """Segment ensemble slices equal standalone segment runs leaf-exact
    (same mode -> identical slot layout too), and equal dense singles in
    canonical pop order (cross mode)."""
    cfg, model, tables, _ = _phold_world(num_hosts=8)
    cfg = dataclasses.replace(cfg, tracker=True, exchange="segment")
    end = 60 * NS_PER_MS
    stride = 3
    ens = run_ensemble_until(
        init_ensemble_state(cfg, model, 2, stride), end, model, tables, cfg,
        rounds_per_chunk=8,
    )
    assert int(ens.events_handled.sum()) > 0
    for r, seed in enumerate(replica_seeds(cfg, 2, stride)):
        sl = replica_slice(ens, r)
        single = _single_run(cfg, model, tables, seed, end, 8)
        fa = jax.tree_util.tree_leaves_with_path(sl)
        for (path, la), lb in zip(fa, jax.tree.leaves(single)):
            assert jnp.array_equal(la, lb), (
                f"replica {r} mismatch at {jax.tree_util.keystr(path)}"
            )
        dense = _single_run(
            dataclasses.replace(cfg, exchange="dense"), model, tables, seed,
            end, 8,
        )
        _assert_pop_order_equal(dense, single, f" (replica {r} vs dense)")


def test_mesh_segment_slices_match_single_dense():
    """The mesh cell of the acceptance bar: a 2x4 Mesh(replica, hosts)
    run with the ppermute-ring segment exchange — which, unlike
    all_to_all, batches under the replica vmap (engine/round.py
    _ring_exchange) — matches single-device DENSE runs slice by slice."""
    assert jax.device_count() == 8
    cfg, model, tables, _ = _phold_world(num_hosts=8)
    cfg = dataclasses.replace(cfg, tracker=True, exchange="segment")
    end = 40 * NS_PER_MS
    stride = 7
    plan = MeshPlan(replicas=2, shards=4, rows=2)
    ens = run_mesh_until(
        init_mesh_state(cfg, model, plan, stride), end, model, tables, cfg,
        plan, rounds_per_chunk=4,
    )
    assert int(ens.events_handled.sum()) > 0
    for r, seed in enumerate(replica_seeds(cfg, 2, stride)):
        single = _single_run(
            dataclasses.replace(cfg, exchange="dense"), model, tables, seed,
            end, 4,
        )
        _assert_mesh_slice_exact(
            mesh_replica_slice(ens, r), single, f" (segment replica {r})"
        )


def test_segment_chaos_capacity_recovers_leaf_exact():
    """Chaos cell of the acceptance bar: an injected capacity fault on
    the onion scenario running exchange='segment' rolls back, regrows,
    replays, and finishes leaf-exact vs a fault-free segment run started
    at the regrown capacity (mirror of test_overlay's dense pin)."""
    from shadow_tpu.runtime import chaos
    from shadow_tpu.runtime.chaos import FaultPlan
    from shadow_tpu.runtime.recovery import RecoveryPolicy, run_until_recovering

    model = _onion(h=10, clients=4)
    cfg, tables = _overlay_world(model, queue_capacity=96, outbox_capacity=48)
    cfg = dataclasses.replace(cfg, exchange="segment")
    end = 200 * NS_PER_MS
    st0 = bootstrap(init_state(cfg, model.init()), model, cfg)
    plan = FaultPlan(faults=[{"kind": "capacity", "at": 1}])
    with chaos.installed(plan):
        final, recoveries = run_until_recovering(
            st0, end, model, tables, cfg, rounds_per_chunk=4,
            policy=RecoveryPolicy(max_recoveries=2, snapshot_interval_chunks=2),
        )
    assert [r["kind"] for r in recoveries] == ["capacity"]
    grown = final.queue.capacity
    assert grown == 2 * cfg.queue_capacity

    cfg2 = dataclasses.replace(cfg, queue_capacity=grown)
    st2 = bootstrap(init_state(cfg2, model.init()), model, cfg2)
    reference = run_until(st2, end, model, tables, cfg2, rounds_per_chunk=4)
    fa = jax.tree_util.tree_leaves_with_path(reference)
    for (path, la), lb in zip(fa, jax.tree.leaves(final)):
        assert jnp.array_equal(la, lb), (
            f"recovered mismatch at {jax.tree_util.keystr(path)}"
        )
    assert int(final.model.streams_done.sum()) > 0
