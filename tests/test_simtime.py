from shadow_tpu.simtime import (
    NS_PER_MS,
    NS_PER_SEC,
    SIM_START_UNIX_NS,
    TIME_MAX,
    fmt_time_ns,
    parse_time_ns,
)


def test_epoch_is_y2k():
    # 2000-01-01T00:00:00Z == 946684800 Unix seconds
    assert SIM_START_UNIX_NS == 946684800 * NS_PER_SEC


def test_parse_time():
    assert parse_time_ns("10 ms") == 10 * NS_PER_MS
    assert parse_time_ns("2 sec") == 2 * NS_PER_SEC
    assert parse_time_ns("2s") == 2 * NS_PER_SEC
    assert parse_time_ns("1 min") == 60 * NS_PER_SEC
    assert parse_time_ns("30") == 30 * NS_PER_SEC
    assert parse_time_ns(5) == 5 * NS_PER_SEC
    assert parse_time_ns("1500 ns") == 1500
    assert parse_time_ns("2.5 us") == 2500


def test_fmt_time():
    assert fmt_time_ns(0).startswith("2000-01-01 00:00:00")
    assert fmt_time_ns(TIME_MAX) == "never"


def test_time_max_headroom():
    # adding a large latency to TIME_MAX must not overflow i64
    assert TIME_MAX + 10 * NS_PER_SEC < (1 << 63) - 1
