"""The packet-pump microscan (engine/pump.py) is a pure accelerator: with
pump_k on, the engine must produce BIT-IDENTICAL state to the unpumped
engine on the flagship tgen workload — same queue contents, TCP fields,
relay/AQM state, RNG counters, sequence counters, and byte/stream
counters — including under loss and shaping (where most pops are the
defer/completion chains the pump exists to batch, and recovery events
exercise every fallback path)."""

import dataclasses
import random

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.round import bootstrap, check_capacity, run_until
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.tgen import TgenModel
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS


def _world(num_hosts, loss, bw_bits, seed=11):
    rng_py = random.Random(seed)
    n_nodes = 4
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "2 ms" ]')
    for i in range(n_nodes):
        for j in range(n_nodes):
            if i < j:
                lat = rng_py.randrange(2, 9)
                lines.append(
                    f'  edge [ source {i} target {j} latency "{lat} ms" '
                    f"packet_loss {loss} ]"
                )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    tables = compute_routing(graph).with_hosts(
        [i % n_nodes for i in range(num_hosts)]
    )
    cfg = EngineConfig(
        num_hosts=num_hosts,
        queue_capacity=192,
        outbox_capacity=32,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
        use_netstack=True,
        deliver_lanes=48,
    )
    model = TgenModel(
        num_hosts=num_hosts,
        num_clients=num_hosts // 2,
        num_servers=num_hosts - num_hosts // 2,
        resp_bytes=40_000,
        pause_ns=30 * NS_PER_MS,
    )
    bw = bw_bits_per_sec_to_refill(bw_bits)
    st = init_state(
        cfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw
    )
    st = bootstrap(st, model, cfg)
    return cfg, model, tables, st


def _run(cfg, model, tables, st, end_ns):
    st = run_until(st, end_ns, model, tables, cfg, rounds_per_chunk=16)
    check_capacity(st)
    return st


def _normalize(st):
    """Canonicalize the queue: slot PLACEMENT is semantically irrelevant
    (pops are key-driven; pumped runs interleave pushes differently), and
    pops tombstone only the (time, tie) keys, leaving stale kind/data/aux
    behind. Rows are sorted by (time, tie) with dead-slot content zeroed,
    so only the live event *sets* must match."""
    import numpy as np

    dead = np.asarray(st.queue.time) >= (1 << 62) - 1
    time = np.asarray(st.queue.time)
    tie = np.where(dead, np.iinfo(np.int64).max, np.asarray(st.queue.tie))
    kind = np.where(dead, 0, np.asarray(st.queue.kind))
    aux = np.where(dead, 0, np.asarray(st.queue.aux))
    data = np.where(dead[:, :, None], 0, np.asarray(st.queue.data))
    order = np.lexsort((tie, time), axis=1)
    oi = np.arange(time.shape[0])[:, None]
    q = st.queue.replace(
        time=jnp.asarray(time[oi, order]),
        tie=jnp.asarray(tie[oi, order]),
        kind=jnp.asarray(kind[oi, order]),
        aux=jnp.asarray(aux[oi, order]),
        data=jnp.asarray(data[oi, order]),
    )
    # iters_done/lanes_live count engine iterations, not simulation state
    return st.replace(
        queue=q, iters_done=st.iters_done * 0, lanes_live=st.lanes_live * 0
    )


def _assert_states_equal(a, b):
    a, b = _normalize(a), _normalize(b)
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        assert jnp.array_equal(la, lb), f"mismatch at {jax.tree_util.keystr(path)}"


@pytest.mark.parametrize("loss,bw", [(0.0, 20_000_000), (0.02, 20_000_000)])
def test_pump_bit_identical_tgen(loss, bw):
    cfg0, model, tables, st0 = _world(32, loss, bw)
    end = 120 * NS_PER_MS
    ref = _run(cfg0, model, tables, st0, end)
    cfgp = dataclasses.replace(cfg0, pump_k=6)
    got = _run(cfgp, model, tables, st0, end)
    assert int(ref.model.streams_done.sum()) > 0  # real traffic flowed
    # pumped iterations must be fewer (the whole point) ...
    assert int(got.iters_done.sum()) < int(ref.iters_done.sum())
    # ... with identical simulation results. iters_done is the only field
    # allowed to differ (it counts engine iterations, not simulation state).
    _assert_states_equal(ref, got)


def test_pump_unshaped_world_matches():
    """No netstack shaping: only P2/P3 apply; defers never occur."""
    cfg0, model, tables, st0 = _world(16, 0.0, 0)
    cfg0 = dataclasses.replace(cfg0, use_netstack=False)
    end = 80 * NS_PER_MS
    ref = _run(cfg0, model, tables, st0, end)
    got = _run(dataclasses.replace(cfg0, pump_k=5), model, tables, st0, end)
    assert int(ref.model.streams_done.sum()) > 0
    _assert_states_equal(ref, got)
