"""Parallel managed tier: hosts sharded across kernel worker processes
with packets on the device engine must reproduce the serial hybrid
scheduler's transfers, guest timelines, and stats exactly — the partition
is an execution detail, never a semantic one (the parallel analogue of
the reference's thread_per_core host scheduling being order-free within a
round, thread_per_core.rs:188-206)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.engine import EngineConfig
from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.runtime.hybrid import HybridScheduler, ParallelHybridScheduler
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"
W = 1 * NS_PER_MS


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    built = {}
    for name in ("tcp_echo_server", "tcp_client"):
        dst = out / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True)
        built[name] = str(dst)
    return built


def _specs(bins, n_pairs, nbytes):
    specs = []
    for i in range(n_pairs):
        specs.append(
            ProcessSpec(host=f"server{i}", args=[bins["tcp_echo_server"], "8080", "1"])
        )
        specs.append(
            ProcessSpec(
                host=f"client{i}",
                args=[bins["tcp_client"], f"server{i}", "8080", str(nbytes)],
                start_ns=(100 + 10 * i) * NS_PER_MS,
            )
        )
    return specs


def _world(n_pairs, loss):
    graph = two_node_graph(10, loss)
    host_names = [f"server{i}" for i in range(n_pairs)] + [
        f"client{i}" for i in range(n_pairs)
    ]
    host_nodes = [0] * n_pairs + [1] * n_pairs
    tables = compute_routing(graph).with_hosts(host_nodes)
    cfg = EngineConfig(
        num_hosts=2 * n_pairs,
        queue_capacity=256,
        outbox_capacity=64,
        runahead_ns=W,
        seed=5,
    )
    return tables, cfg, host_names, host_nodes


def _run_serial(tmp_path, bins, n_pairs, loss, nbytes, until_s):
    tables, cfg, host_names, host_nodes = _world(n_pairs, loss)
    k = NetKernel(
        tables,
        host_names=host_names,
        host_nodes=host_nodes,
        seed=5,
        data_dir=tmp_path / "serial",
        window_ns=W,
    )
    runner = HybridScheduler(k, tables, cfg)
    procs = [k.add_process(s) for s in _specs(bins, n_pairs, nbytes)]
    try:
        runner.run(until_s * NS_PER_SEC)
    finally:
        k.shutdown()
    info = [
        {
            "host": p.host.name,
            "stdout": p.stdout(),
            "exit_code": p.exit_code,
            "syscalls": [s for _, s, _ in p.syscall_log],
        }
        for p in procs
    ]
    return k.stats(), sorted(k.event_log), info


def _run_parallel(tmp_path, bins, n_pairs, loss, nbytes, until_s, num_workers):
    tables, cfg, host_names, host_nodes = _world(n_pairs, loss)
    sched = ParallelHybridScheduler(
        tables,
        cfg,
        host_names=host_names,
        host_nodes=host_nodes,
        specs=_specs(bins, n_pairs, nbytes),
        num_workers=num_workers,
        seed=5,
        data_dir=tmp_path / f"par{num_workers}",
    )
    try:
        try:
            sched.run(until_s * NS_PER_SEC)
        finally:
            sched.shutdown()
        stats = sched.stats()
        log = sorted(sched.event_log())
        info = [
            {
                "host": p["host"],
                "stdout": p["stdout"],
                "exit_code": p["exit_code"],
                "syscalls": p["syscalls"],
            }
            for p in sched.proc_info()
        ]
        assert sched.device_passes > 0
        return stats, log, info
    finally:
        sched.close()


@pytest.mark.parametrize("loss", [0.0, 0.03])
def test_parallel_matches_serial(tmp_path, bins, loss):
    n_pairs, nbytes, until_s = 3, 30_000, 90
    s_stats, s_log, s_info = _run_serial(tmp_path, bins, n_pairs, loss, nbytes, until_s)
    p_stats, p_log, p_info = _run_parallel(
        tmp_path, bins, n_pairs, loss, nbytes, until_s, num_workers=3
    )
    by_host_s = {i["host"]: i for i in s_info}
    by_host_p = {i["host"]: i for i in p_info}
    assert by_host_s.keys() == by_host_p.keys()
    for h in by_host_s:
        assert by_host_s[h]["stdout"] == by_host_p[h]["stdout"], h
        assert by_host_s[h]["exit_code"] == by_host_p[h]["exit_code"], h
        assert by_host_s[h]["syscalls"] == by_host_p[h]["syscalls"], h
    assert s_log == p_log
    assert s_stats == p_stats
    # every client actually echoed its payload
    for h, i in by_host_p.items():
        if h.startswith("client"):
            assert f"echoed {nbytes}/{nbytes} bytes".encode() in i["stdout"], h


def test_parallel_worker_count_invariant(tmp_path, bins):
    """K must not change any outcome (partition is execution detail)."""
    a = _run_parallel(tmp_path, bins, 2, 0.02, 20_000, 90, num_workers=2)
    b = _run_parallel(tmp_path, bins, 2, 0.02, 20_000, 90, num_workers=4)
    assert a[0] == b[0]
    assert a[1] == b[1]
    assert {i["host"]: i["stdout"] for i in a[2]} == {i["host"]: i["stdout"] for i in b[2]}
