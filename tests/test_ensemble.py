"""Ensemble plane (engine/ensemble.py, runtime/ensemble.py): R vmapped
replicas in one device program, with EXACT per-replica independence.

Contracts pinned here:

  * replica r of an R-replica ensemble is leaf-identical to a
    single-replica run with the derived seed (seed + r * stride) — on
    phold and tgen, plain and pump engines, tracker leaves included;
  * the pipelined ensemble driver is leaf-exact vs the synchronous one
    (per-replica quiescence rows restore now/rounds exactly);
  * a checkpoint taken mid-ensemble-run resumes to the bit-identical
    final [R, ...] state, and each resumed slice still matches its
    single-replica run;
  * one replica's capacity blowup raises a CapacityError naming the
    replica, and rollback-and-regrow recovers the WHOLE batch to a
    final state leaf-exact vs starting with the larger capacity;
  * engine="megakernel" resolves to the (bit-identical) pump under the
    ensemble vmap.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from test_pipeline import _phold_world
from test_pump import _world as _tgen_world

from shadow_tpu.engine.ensemble import (
    ensemble_engine_cfg,
    grow_ensemble_state,
    init_ensemble_state,
    num_replicas,
    replica_seeds,
    replica_slice,
    run_ensemble_until,
)
from shadow_tpu.engine.round import CapacityError, bootstrap, run_until
from shadow_tpu.engine.state import init_state, state_to_host
from shadow_tpu.netstack import bw_bits_per_sec_to_refill
from shadow_tpu.simtime import NS_PER_MS


def _assert_leaves_exact(a, b, what=""):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        assert jnp.array_equal(la, lb), (
            f"mismatch{what} at {jax.tree_util.keystr(path)}"
        )


def _single_run(cfg, model, tables, seed, end, rounds_per_chunk, bw=None):
    """A single-replica run exactly as a user with this seed would run it."""
    rcfg = dataclasses.replace(cfg, seed=seed)
    st = init_state(
        rcfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw
    )
    st = bootstrap(st, model, rcfg)
    return run_until(st, end, model, tables, rcfg, rounds_per_chunk=rounds_per_chunk)


def test_ensemble_matches_single_phold_plain():
    cfg, model, tables, _ = _phold_world()
    cfg = dataclasses.replace(cfg, tracker=True)
    end = 40 * NS_PER_MS
    stride = 7
    ens0 = init_ensemble_state(cfg, model, 3, stride)
    ens = run_ensemble_until(ens0, end, model, tables, cfg, rounds_per_chunk=4)
    assert num_replicas(ens) == 3
    totals = set()
    for r, seed in enumerate(replica_seeds(cfg, 3, stride)):
        single = _single_run(cfg, model, tables, seed, end, 4)
        _assert_leaves_exact(replica_slice(ens, r), single, f" (replica {r})")
        totals.add(int(single.events_handled.sum()))
    assert len(totals) > 1  # seeds actually diverged the trajectories


@pytest.mark.parametrize("engine,k", [("plain", 0), ("pump", 3)])
def test_ensemble_matches_single_tgen(engine, k):
    cfg0, model, tables, _ = _tgen_world(8, 0.02, 20_000_000, seed=3)
    cfg = dataclasses.replace(cfg0, tracker=True, engine=engine, pump_k=k)
    bw = bw_bits_per_sec_to_refill(20_000_000)
    end = 30 * NS_PER_MS
    ens0 = init_ensemble_state(
        cfg, model, 2, 3, tx_bytes_per_interval=bw, rx_bytes_per_interval=bw
    )
    ens = run_ensemble_until(ens0, end, model, tables, cfg, rounds_per_chunk=8)
    for r, seed in enumerate(replica_seeds(cfg, 2, 3)):
        single = _single_run(cfg, model, tables, seed, end, 8, bw=bw)
        _assert_leaves_exact(replica_slice(ens, r), single, f" (replica {r})")


def test_ensemble_pipelined_matches_sync():
    cfg, model, tables, _ = _phold_world(seed=17)
    cfg = dataclasses.replace(cfg, tracker=True)
    end = 30 * NS_PER_MS
    ens0 = init_ensemble_state(cfg, model, 3, 2)
    sync = run_ensemble_until(
        ens0, end, model, tables, cfg, rounds_per_chunk=4, pipeline=False
    )
    piped = run_ensemble_until(
        ens0, end, model, tables, cfg, rounds_per_chunk=4, pipeline=True
    )
    assert int(piped.events_handled.sum()) > 0
    _assert_leaves_exact(sync, piped)


def test_ensemble_checkpoint_resume_exact(tmp_path):
    """A checkpoint tapped at a chunk boundary mid-ensemble-run resumes
    to the bit-identical final batch, and every resumed slice still
    matches its single-replica run — the determinism contract survives
    serializing the whole [R, ...] state."""
    from shadow_tpu.runtime.checkpoint import (
        CheckpointManager,
        StateTap,
        load_checkpoint,
    )

    cfg, model, tables, _ = _phold_world(seed=29)
    cfg = dataclasses.replace(cfg, tracker=True)
    end = 40 * NS_PER_MS
    ens0 = init_ensemble_state(cfg, model, 2, 1)

    straight = run_ensemble_until(ens0, end, model, tables, cfg, rounds_per_chunk=4)

    ckpt = CheckpointManager(str(tmp_path), 10 * NS_PER_MS, "fp-test")
    tap = StateTap(checkpoints=ckpt)
    run_ensemble_until(
        ens0, end, model, tables, cfg, rounds_per_chunk=4, on_state=tap
    )
    assert ckpt.written, "the cadence must have written a checkpoint"

    # written[-1]: the manager prunes older checkpoints (keep=2)
    restored, meta = load_checkpoint(ckpt.written[-1], ens0, "fp-test")
    assert meta["queue_capacity"] == cfg.queue_capacity  # [-1] axis, not H
    resumed = run_ensemble_until(
        restored, end, model, tables, cfg, rounds_per_chunk=4
    )
    _assert_leaves_exact(straight, resumed)
    for r, seed in enumerate(replica_seeds(cfg, 2, 1)):
        single = _single_run(cfg, model, tables, seed, end, 4)
        _assert_leaves_exact(replica_slice(resumed, r), single, f" (replica {r})")


def test_ensemble_checkpoint_straddling_quiescence_exact(tmp_path):
    """Regression: a checkpoint that lands AFTER one replica quiesced but
    BEFORE the batch finished must still resume to the bit-identical
    final state. The early replica keeps taking idle rounds on device
    while the slow one drains, so an unpatched snapshot would bake those
    extra now/round-counter updates in (_patch_snapshot) and the resumed
    driver would re-record them (entry prefill). seed=11 + rpc=1 makes
    the replicas quiesce in different chunks, so the cadence provably
    produces a straddling checkpoint (asserted, not assumed)."""
    import numpy as np

    from shadow_tpu import equeue
    from shadow_tpu.runtime.checkpoint import (
        CheckpointManager,
        StateTap,
        load_checkpoint,
    )

    cfg, model, tables, _ = _phold_world(seed=11)
    cfg = dataclasses.replace(cfg, tracker=True)
    end = 40 * NS_PER_MS
    ens0 = init_ensemble_state(cfg, model, 2, 1)
    ckpt = CheckpointManager(str(tmp_path), 2 * NS_PER_MS, "fp", keep=50)
    straight = run_ensemble_until(
        ens0, end, model, tables, cfg, rounds_per_chunk=1,
        on_state=StateTap(checkpoints=ckpt),
    )
    straddling = []
    for p in ckpt.written:
        st, _ = load_checkpoint(p, ens0, "fp")
        quiet = (
            np.asarray(jnp.min(equeue.next_time(st.queue), axis=-1)) >= end
        )
        if quiet.any() and not quiet.all():
            straddling.append(st)
    assert straddling, "scenario regressed: no checkpoint straddles"
    resumed = run_ensemble_until(
        straddling[-1], end, model, tables, cfg, rounds_per_chunk=1
    )
    _assert_leaves_exact(straight, resumed)


def test_ensemble_capacity_error_names_replica():
    cfg, model, tables, _ = _phold_world(queue_capacity=2)
    cfg = dataclasses.replace(cfg, outbox_capacity=1)
    ens0 = init_ensemble_state(cfg, model, 3, 1)
    with pytest.raises(CapacityError, match=r"replica \d of 3") as ei:
        run_ensemble_until(
            ens0, 40 * NS_PER_MS, model, tables, cfg, rounds_per_chunk=4
        )
    assert ei.value.replica is not None
    assert 0 <= ei.value.replica < 3


def test_ensemble_recovery_regrows_whole_batch():
    """Rollback-and-regrow through the shared recovery loop: one
    replica's overflow rolls the whole batch back, every replica's
    buffers widen together, and the recovered final state is leaf-exact
    vs an ensemble that started at the larger capacity."""
    from shadow_tpu.runtime.recovery import RecoveryPolicy, run_until_recovering

    cfg_small, model, tables, _ = _phold_world(queue_capacity=2)
    end = 60 * NS_PER_MS
    R = 2

    def factory(run_cfg):
        def run(st, on_state=None):
            return run_ensemble_until(
                st, end, model, tables, run_cfg,
                rounds_per_chunk=4, on_state=on_state,
            )

        return run

    ens_small = init_ensemble_state(cfg_small, model, R, 1)
    final, recoveries = run_until_recovering(
        ens_small,
        end,
        cfg=cfg_small,
        policy=RecoveryPolicy(max_recoveries=4, snapshot_interval_chunks=2),
        runner_factory=factory,
        grow_fn=grow_ensemble_state,
    )
    assert recoveries, "the tiny queue must have overflowed at least once"
    assert "replica" in recoveries[0]  # the record names the failing world
    grown_cap = recoveries[-1]["queue_capacity"]
    assert grown_cap > cfg_small.queue_capacity

    cfg_big = dataclasses.replace(cfg_small, queue_capacity=grown_cap)
    ens_big = run_ensemble_until(
        init_ensemble_state(cfg_big, model, R, 1),
        end, model, tables, cfg_big, rounds_per_chunk=4,
    )
    _assert_leaves_exact(final, ens_big)


def test_megakernel_falls_back_to_pump_under_vmap():
    cfg, _, _, _ = _phold_world()
    mk = dataclasses.replace(cfg, engine="megakernel", pump_k=0)
    resolved = ensemble_engine_cfg(mk)
    assert resolved.engine == "pump" and resolved.pump_k == 8
    assert resolved.ensemble
    mk2 = dataclasses.replace(cfg, engine="megakernel", pump_k=4)
    assert ensemble_engine_cfg(mk2).pump_k == 4
    # non-megakernel engines pass through except for the done-mask flag
    plain = ensemble_engine_cfg(cfg)
    assert plain.ensemble and plain.engine == cfg.engine
    assert dataclasses.replace(plain, ensemble=False) == cfg


def test_run_ensemble_until_rejects_single_state():
    cfg, model, tables, st0 = _phold_world()
    with pytest.raises(ValueError, match="ensemble state"):
        run_ensemble_until(st0, 10 * NS_PER_MS, model, tables, cfg)


def test_state_to_host_roundtrips_ensemble():
    cfg, model, tables, _ = _phold_world()
    ens = init_ensemble_state(cfg, model, 2, 1)
    host = state_to_host(ens)
    assert host.now.shape == (2,)
    assert host.queue.time.shape[-1] == cfg.queue_capacity
