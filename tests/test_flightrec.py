"""Flight recorder + metrics plane (docs/observability.md;
runtime/flightrec.py).

The contracts under test:

  * **black box on every failure path** — a chaos-injected capacity
    fault and a chaos-injected watchdog stall each leave a readable
    `flight-recorder.json` whose last sample matches the failing (resp.
    last successfully fetched) chunk's probe from a fault-free run of
    the same world — the drivers record the probe BEFORE raising;
  * **zero extra device syncs** — enabling the metrics stream adds not
    one `jax.device_get` over a plain run (the recorder reads only the
    probes the driver fetched anyway);
  * survivable degradations (engine fallback, sweep quarantine) also
    dump, and the unit surfaces (ring bound, deltas, prom snapshot,
    summary renderer) hold shape.
"""

import json
import pathlib
import sys
import types

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent))
from test_pipeline import _phold_world  # noqa: E402

from shadow_tpu.engine.round import (  # noqa: E402
    CapacityError,
    ChunkProbe,
    WatchdogExpired,
    run_until,
)
from shadow_tpu.runtime import chaos, flightrec  # noqa: E402
from shadow_tpu.runtime.chaos import FaultPlan, run_with_engine_ladder  # noqa: E402
from shadow_tpu.runtime.flightrec import (  # noqa: E402
    FlightRecorder,
    failure_record,
    load_series,
    render_summary,
    render_summary_file,
)
from shadow_tpu.runtime.recovery import (  # noqa: E402
    RecoveryPolicy,
    run_until_recovering,
)
from shadow_tpu.simtime import NS_PER_MS  # noqa: E402

pytestmark = pytest.mark.metrics


def _probe(**kw) -> ChunkProbe:
    """A ChunkProbe with every cumulative lane defaulted to 0."""
    import dataclasses

    fields = {f.name: 0 for f in dataclasses.fields(ChunkProbe)}
    fields.update(kw)
    return ChunkProbe(**fields)


# ---- unit surfaces ------------------------------------------------------


def test_metrics_stream_rotates_at_size_cap(tmp_path):
    """Satellite (ISSUE 11): the JSONL stream rotates at
    general.metrics_max_mb keeping metrics_keep numbered segments, so a
    week-long daemon soak cannot fill the disk — and the live path
    always holds the newest samples."""
    import os

    mf = tmp_path / "m.jsonl"
    rec = FlightRecorder(
        num_hosts=8, metrics_path=str(mf),
        metrics_max_bytes=2_000, metrics_keep=2,
    )
    for i in range(120):
        rec.observe(_probe(now=(i + 1) * 1000, events_handled=(i + 1) * 10))
    rec.close()
    assert rec.rotations >= 2
    # keep=2: live file + .1 + .2 and nothing older
    assert mf.exists() and (tmp_path / "m.jsonl.1").exists()
    assert (tmp_path / "m.jsonl.2").exists()
    assert not (tmp_path / "m.jsonl.3").exists()
    # every segment stays under cap + one line of slack
    for p in (mf, tmp_path / "m.jsonl.1", tmp_path / "m.jsonl.2"):
        assert os.path.getsize(p) < 2_600
    # every segment parses; the newest sample lives in the newest
    # segment that has samples (the live file may hold only the
    # rotation marker when the cap fired on the final line)
    def _samples(p):
        return [
            json.loads(ln) for ln in p.read_text().splitlines()
            if json.loads(ln).get("type") == "sample"
        ]

    live, older = _samples(mf), _samples(tmp_path / "m.jsonl.1")
    newest = (live or older)[-1]["chunk"]
    assert newest == 119
    if live and older:
        assert older[-1]["chunk"] < live[0]["chunk"]  # segments ordered


def test_ring_bound_and_sample_deltas(tmp_path):
    rec = FlightRecorder(num_hosts=8, ring=4,
                         metrics_path=str(tmp_path / "m.jsonl"))
    for i in range(10):
        rec.observe(
            _probe(
                now=(i + 1) * 1000,
                events_handled=(i + 1) * 10,
                packets_sent=(i + 1) * 2,
                iters=(i + 1) * 4,
                lanes_live=(i + 1) * 16,
                rounds_live=(i + 1) * 2,
                win_ns_sum=(i + 1) * 500,
            )
        )
    rec.close()
    assert len(rec.samples) == 4  # bounded ring
    last = rec.samples[-1]
    assert last["chunk"] == 9
    # per-chunk deltas of the cumulative lanes
    assert last["dt_ns"] == 1000 and last["events"] == 10
    assert last["win_ns_mean"] == 250.0  # 500 ns over 2 live rounds
    # occupancy: 16 live lanes over 4 iterations of 8 lanes each
    assert last["occupancy"] == 0.5
    # cumulative totals ride every sample (the black-box matcher's key)
    assert last["events_total"] == 100
    # the stream kept ALL 10 samples even though the ring holds 4
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().splitlines()]
    assert sum(1 for l in lines if l["type"] == "sample") == 10


def test_events_counters_and_prom_snapshot(tmp_path):
    rec = FlightRecorder(num_hosts=4, prom_path=str(tmp_path / "m.prom"))
    rec.observe(_probe(now=5000, events_handled=7, packets_sent=3))
    rec.event("recovery", kind_detail="capacity")
    rec.event("engine_fallback", to="plain")
    rec.event("compile_cache", hit=True)
    rec.event("compile_cache", hit=False, wall_s=1.5)
    rec.event("checkpoint", wall_s=0.1)
    assert rec.counters["recoveries"] == 1
    assert rec.counters["engine_fallbacks"] == 1
    assert rec.counters["cache_hits"] == 1 and rec.counters["cache_misses"] == 1
    assert rec.counters["checkpoints"] == 1
    # the next sample carries the cumulative counters
    s = rec.observe(_probe(now=6000, events_handled=9, packets_sent=3))
    assert s["recoveries"] == 1 and s["engine_fallbacks"] == 1
    assert rec.write_prom(extra_gauges={"shadow_tpu_sweep_queue_depth": 3})
    prom = (tmp_path / "m.prom").read_text()
    assert "shadow_tpu_events_total 9" in prom
    assert "shadow_tpu_recoveries_total 1" in prom
    assert "shadow_tpu_compile_cache_hits_total 1" in prom
    assert "shadow_tpu_sweep_queue_depth 3" in prom
    assert "# TYPE shadow_tpu_events_total gauge" in prom


def test_failure_record_maps_exception_classes():
    err = CapacityError("boom")
    err.queue_overflow, err.injected = 5, True
    rec = failure_record(err)
    assert rec["kind"] == "capacity" and rec["queue_overflow"] == 5
    assert rec["injected"] is True
    w = failure_record(WatchdogExpired(3, 0.5))
    assert w["kind"] == "watchdog" and w["chunk"] == 3
    assert w["deadline_s"] == 0.5
    assert failure_record(ValueError("x"))["kind"] == "ValueError"


def test_summary_renderer_has_percentile_rows(tmp_path):
    rec = FlightRecorder(num_hosts=8, metrics_path=str(tmp_path / "m.jsonl"))
    for i in range(12):
        rec.observe(_probe(now=(i + 1) * 1000, events_handled=(i + 1) * 5,
                           iters=i + 1, lanes_live=(i + 1) * 2))
    rec.event("recovery", note="x")
    rec.close()
    samples, events, meta = load_series(str(tmp_path / "m.jsonl"))
    assert len(samples) == 12 and len(events) == 1
    out = render_summary(samples, events, meta)
    for token in ("p50", "p90", "p99", "12 samples", "dt_ns", "recovery"):
        assert token in out, out


# ---- black-box dumps on the chaos failure matrix ------------------------


@pytest.fixture(scope="module")
def fault_free():
    """One shared fault-free reference run: (world, per-chunk probes).
    Module-scoped — the capacity and watchdog black-box tests compare
    against the same deterministic probe series."""
    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    probes = []
    run_until(st0, end, model, tables, cfg,
              rounds_per_chunk=4, on_chunk=probes.append)
    return cfg, model, tables, st0, end, probes


def test_capacity_fault_blackbox_last_sample_is_failing_chunk(
    tmp_path, fault_free
):
    """An injected CapacityError (fail-fast: no recovery budget) leaves a
    valid flight-recorder.json whose LAST sample is the failing chunk's
    probe — the driver records the probe before raising, so the black
    box sees the chunk that died, byte-for-byte equal to the fault-free
    run's probe at that chunk."""
    cfg, model, tables, st0, end, probes = fault_free
    box = tmp_path / "flight-recorder.json"
    rec = FlightRecorder(num_hosts=cfg.num_hosts, blackbox_path=str(box))
    plan = FaultPlan(faults=[{"kind": "capacity", "at": 2}])
    with chaos.installed(plan), flightrec.installed(rec):
        with pytest.raises(CapacityError):
            run_until_recovering(
                st0, end, model, tables, cfg, rounds_per_chunk=4,
                policy=RecoveryPolicy(max_recoveries=0),
            )
    doc = json.loads(box.read_text())
    assert doc["format"] == "shadow-tpu-flight-recorder-v1"
    assert doc["failure"]["kind"] == "capacity"
    assert doc["failure"]["injected"] is True
    last = doc["samples"][-1]
    assert last is doc["samples"][-1] and last == doc["last_sample"]
    ref = probes[2]  # the fault fires at chunk 2: its probe is healthy
    assert last["chunk"] == 2
    assert last["now_ns"] == ref.now
    assert last["events_total"] == ref.events_handled
    assert last["packets_total"] == ref.packets_sent
    # the summary renderer reads the black box directly
    out = render_summary_file(str(box))
    assert "FAILURE: kind=capacity" in out and "p50" in out


def test_watchdog_stall_blackbox_dump(tmp_path, fault_free):
    """A chaos stall blowing the watchdog past its recovery budget
    leaves a black box: failure kind `watchdog` naming the chunk, the
    survived recovery counted, and the last sample matching the last
    successfully fetched chunk of a fault-free run (the stalled chunk's
    probe never arrived — that is what a stall IS)."""
    cfg, model, tables, st0, end, probes = fault_free
    box = tmp_path / "flight-recorder.json"
    rec = FlightRecorder(num_hosts=cfg.num_hosts, blackbox_path=str(box))
    plan = FaultPlan(
        faults=[{"kind": "stall", "at": 1, "stall_s": 0.3, "count": -1}]
    )
    with chaos.installed(plan), flightrec.installed(rec):
        with pytest.raises(WatchdogExpired):
            run_until_recovering(
                st0, end, model, tables, cfg, rounds_per_chunk=4,
                policy=RecoveryPolicy(max_recoveries=1),
                watchdog_s=0.05,
            )
    doc = json.loads(box.read_text())
    assert doc["failure"]["kind"] == "watchdog"
    assert doc["failure"]["chunk"] == 1
    assert doc["failure"]["deadline_s"] == 0.05
    assert doc["counters"]["recoveries"] == 1
    # chunk 0 fetched cleanly (twice: once per attempt); chunk 1 stalled
    last = doc["samples"][-1]
    assert last["chunk"] == 0
    assert last["now_ns"] == probes[0].now
    assert last["events_total"] == probes[0].events_handled
    # the survived recovery is in the event log
    kinds = [e["kind"] for e in doc["events"]]
    assert "recovery" in kinds


def test_engine_fallback_writes_blackbox(tmp_path):
    """The engine ladder's fallback is a survivable degradation: the run
    completes, but a black box records the moment the ladder acted."""
    import dataclasses

    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    box = tmp_path / "flight-recorder.json"
    rec = FlightRecorder(num_hosts=cfg.num_hosts, blackbox_path=str(box))
    pump_cfg = dataclasses.replace(cfg, engine="pump", pump_k=3)
    plan = FaultPlan(faults=[{"kind": "compile", "target": "pump"}])
    with chaos.installed(plan), flightrec.installed(rec):
        final, fallbacks = run_with_engine_ladder(
            pump_cfg,
            lambda c: run_until(st0, end, model, tables, c,
                                rounds_per_chunk=4),
        )
    assert len(fallbacks) == 1  # the run survived on plain
    doc = json.loads(box.read_text())
    assert doc["failure"]["kind"] == "engine_fallback"
    assert doc["failure"]["recovered"] is True
    assert doc["failure"]["to"] == "plain"
    assert doc["counters"]["engine_fallbacks"] == 1


def test_sweep_quarantine_writes_blackbox(tmp_path):
    """A quarantined sweep job leaves TWO black boxes: one in its own
    data directory (forensics travel with the job's outputs) and the
    service-level one."""
    from shadow_tpu.runtime.sweep import Batch, SweepService

    svc = SweepService.__new__(SweepService)
    svc.spec = types.SimpleNamespace(retry_max=0, retry_backoff_s=0.0)
    svc.clock_ns = 0
    svc.job_attempts = {}
    svc.job_records = {}
    svc.job_progress = {"j0": {"now_ns": 0, "events": 0}}
    svc.batches = []
    svc.recorder = FlightRecorder(
        blackbox_path=str(tmp_path / "flight-recorder.json")
    )
    job = types.SimpleNamespace(
        name="j0", entry="e", seed=1, priority=0, arrival_ns=0,
        group_key="g" * 16,
        config=types.SimpleNamespace(
            general=types.SimpleNamespace(
                data_directory=str(tmp_path / "jobs" / "j0")
            )
        ),
    )
    batch = Batch(jobs=[job], base_seed=1, stride=1, priority=0,
                  arrival_ns=0, group_key=job.group_key, index=0)
    err = CapacityError("saturated")
    err.queue_overflow = 3
    svc._handle_failure(batch, err, pending=[])
    assert svc.job_records["j0"]["status"] == "failed"
    for path in (tmp_path / "jobs" / "j0" / "flight-recorder.json",
                 tmp_path / "flight-recorder.json"):
        doc = json.loads(path.read_text())
        assert doc["failure"]["kind"] == "capacity"
        assert doc["failure"]["job"] == "j0"
        assert doc["failure"]["queue_overflow"] == 3
    # the batch failure is an event in the service telemetry
    assert "batch_failure" in [e["kind"] for e in svc.recorder.events]


# ---- the zero-extra-syncs pin ------------------------------------------


def test_metrics_stream_adds_zero_device_fetches(tmp_path, monkeypatch):
    """Enabling the full metrics plane (recorder + JSONL stream) costs
    ZERO additional jax.device_get calls over a plain run: every sample
    is a delta of the probe the driver fetched anyway."""
    import jax

    cfg, model, tables, st0 = _phold_world()
    end = 40 * NS_PER_MS
    calls = {"n": 0}
    orig = jax.device_get

    def counting(x):
        calls["n"] += 1
        return orig(x)

    monkeypatch.setattr(jax, "device_get", counting)

    run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    plain = calls["n"]
    assert plain > 0  # the probe fetches are counted

    calls["n"] = 0
    rec = FlightRecorder(num_hosts=cfg.num_hosts,
                         metrics_path=str(tmp_path / "m.jsonl"))
    with flightrec.installed(rec):
        run_until(st0, end, model, tables, cfg, rounds_per_chunk=4)
    rec.close()
    assert len(rec.samples) > 0  # the plane was actually on
    assert calls["n"] == plain  # and cost zero extra fetches
