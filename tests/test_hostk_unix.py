"""Unix-domain socket tests: real guest binaries under the shim
(reference: src/main/host/descriptor/socket/unix.rs stream/dgram incl.
abstract namespace + socket/abstract_unix_ns.rs; paired-test pattern of
src/test/CMakeLists.txt add_linux_tests/add_shadow_tests)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def guest_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    bins = {}
    for name in ("unix_guest", "unix_echo_pair"):
        dst = out / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True)
        bins[name] = str(dst)
    return bins


def _one_host_kernel(tmp_path):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    return NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / "data")


def test_unix_guest_native(tmp_path, guest_bins):
    """The same binary must pass on the real kernel (paired-test contract:
    behavior under the simulator matches native Linux)."""
    r = subprocess.run([guest_bins["unix_guest"]], capture_output=True, text=True, cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "unix all ok" in r.stdout


def test_unix_guest_under_shim(tmp_path, guest_bins):
    k = _one_host_kernel(tmp_path)
    p = k.add_process(ProcessSpec(host="box", args=[guest_bins["unix_guest"]]))
    try:
        k.run(2 * NS_PER_SEC)
    finally:
        k.shutdown()
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "unix all ok" in out
    assert k.syscall_counts["socketpair"] == 1
    assert k.syscall_counts["bind"] >= 3


def test_unix_echo_two_processes_same_host(tmp_path, guest_bins):
    """Blocking accept/recv across two managed processes on one host."""
    k = _one_host_kernel(tmp_path)
    srv = k.add_process(
        ProcessSpec(host="box", args=[guest_bins["unix_echo_pair"], "server", "echo", "5"])
    )
    cli = k.add_process(
        ProcessSpec(
            host="box",
            args=[guest_bins["unix_echo_pair"], "client", "echo", "5", "3"],
            start_ns=50 * NS_PER_MS,
        )
    )
    try:
        k.run(3 * NS_PER_SEC)
    finally:
        k.shutdown()
    assert srv.exit_code == 0, srv.stdout() + srv.stderr()
    assert cli.exit_code == 0, cli.stdout() + cli.stderr()
    assert b"server echoed 5" in srv.stdout()
    assert b"client done 5" in cli.stdout()


def test_unix_echo_deterministic(tmp_path, guest_bins):
    logs = []
    for sub in ("a", "b"):
        k = _one_host_kernel(tmp_path / sub)
        srv = k.add_process(
            ProcessSpec(host="box", args=[guest_bins["unix_echo_pair"], "server", "e2", "4"])
        )
        cli = k.add_process(
            ProcessSpec(
                host="box",
                args=[guest_bins["unix_echo_pair"], "client", "e2", "4", "2"],
                start_ns=10 * NS_PER_MS,
            )
        )
        try:
            k.run(2 * NS_PER_SEC)
        finally:
            k.shutdown()
        logs.append((k.event_log, [s for _, s, _ in srv.syscall_log + cli.syscall_log]))
    assert logs[0] == logs[1]
