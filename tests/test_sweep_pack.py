"""Device-free unit tests for the sweep scheduler's pure seams: the
pack_jobs batching decision (runtime/sweep.py), the compile cache's
keying/counting (runtime/compile_cache.py), and sweep spec expansion
(config/sweep.py)."""

import numpy as np
import pytest

from shadow_tpu.config.sweep import SweepJob, load_sweep_spec
from shadow_tpu.runtime.compile_cache import CompileCache, state_signature
from shadow_tpu.runtime.sweep import pack_jobs


def _job(seed, group="g1", priority=0, arrival=0):
    return SweepJob(
        name=f"j-s{seed}",
        entry="j",
        seed=seed,
        priority=priority,
        arrival_ns=arrival,
        config=None,
        raw_config={},
        group_key=group,
    )


# --- pack_jobs ----------------------------------------------------------


def test_pack_consecutive_seeds_one_batch():
    batches = pack_jobs([_job(s) for s in range(8)], capacity=8)
    assert len(batches) == 1
    b = batches[0]
    assert b.replicas == 8 and b.base_seed == 0 and b.stride == 1


def test_pack_caps_at_capacity():
    batches = pack_jobs([_job(s) for s in range(8)], capacity=3)
    assert [b.replicas for b in batches] == [3, 3, 2]
    assert [b.base_seed for b in batches] == [0, 3, 6]
    assert all(b.stride == 1 for b in batches)


def test_pack_arithmetic_progression_stride():
    """Replica r of an ensemble MUST be seeded base + r*stride
    (rng.replica_keys), so only arithmetic progressions may fold."""
    batches = pack_jobs([_job(s) for s in (3, 5, 7)], capacity=8)
    assert len(batches) == 1
    assert batches[0].stride == 2 and batches[0].base_seed == 3


def test_pack_non_progression_splits():
    batches = pack_jobs([_job(s) for s in (1, 4, 6)], capacity=8)
    # greedy from the sorted front: [1, 4] (stride 3), then [6]
    assert [(b.base_seed, b.replicas, b.stride) for b in batches] == [
        (1, 2, 3),
        (6, 1, 1),
    ]


def test_pack_groups_by_fingerprint_and_priority():
    jobs = [_job(0, "gA"), _job(1, "gA"), _job(0, "gB"), _job(2, "gA", priority=5)]
    batches = pack_jobs(jobs, capacity=8)
    # different fingerprints and different priorities never share a batch
    assert len(batches) == 3
    assert batches[0].priority == 5  # priority order in the plan
    keys = {(b.group_key, b.priority) for b in batches}
    assert keys == {("gA", 0), ("gB", 0), ("gA", 5)}


def test_pack_deterministic_and_indexed():
    jobs = [_job(s) for s in (9, 1, 5, 3, 7)]
    a = pack_jobs(jobs, capacity=4)
    b = pack_jobs(list(reversed(jobs)), capacity=4)
    assert [(x.base_seed, x.replicas, x.stride) for x in a] == [
        (y.base_seed, y.replicas, y.stride) for y in b
    ]
    assert [x.index for x in a] == list(range(len(a)))
    # seeds 1,3,5,7 fold (stride 2, cap 4); 9 overflows to its own batch
    assert [(x.base_seed, x.replicas) for x in a] == [(1, 4), (9, 1)]


def test_pack_duplicate_seed_across_entries_stays_separate():
    """Two spec entries over the same world with the same seed: replica
    streams must be distinct (stride >= 1), so they run as separate
    batches — never a stride-0 'progression'."""
    a = _job(0)
    b = _job(0)
    b.name, b.entry = "k-s0", "k"
    batches = pack_jobs([a, b, _job(1)], capacity=8)
    assert sorted(x.replicas for x in batches) == [1, 2]
    assert all(x.stride >= 1 for x in batches)


def test_pack_rejects_bad_capacity():
    with pytest.raises(ValueError, match="capacity"):
        pack_jobs([_job(0)], capacity=0)


# --- CompileCache -------------------------------------------------------


def _state(shape=(4, 8)):
    return {"a": np.zeros(shape, np.int64), "b": np.zeros(shape[0], np.float32)}


def test_compile_cache_counts_hits_and_misses():
    cache = CompileCache()
    built = []

    def build():
        built.append(1)
        return "exe%d" % len(built)

    st = _state()
    assert cache.get("k", st, "cfg", build) == "exe1"
    assert cache.get("k", st, "cfg", build) == "exe1"  # hit: same everything
    assert (cache.misses, cache.hits) == (1, 1)
    assert len(built) == 1
    assert cache.stats()["compiles"] == 1
    assert cache.stats()["hit_rate"] == 0.5


def test_compile_cache_shape_mismatch_never_aliases():
    """A too-coarse caller key must compile a second entry, never run
    the wrong executable: the cache appends the state signature."""
    cache = CompileCache()
    n = [0]

    def build():
        n[0] += 1
        return f"exe{n[0]}"

    assert cache.get("k", _state((4, 8)), "cfg", build) == "exe1"
    # same caller key, regrown buffers -> different shapes -> fresh entry
    assert cache.get("k", _state((4, 16)), "cfg", build) == "exe2"
    # same shapes, different static cfg -> fresh entry
    assert cache.get("k", _state((4, 8)), "cfg2", build) == "exe3"
    assert cache.misses == 3 and cache.hits == 0


def test_state_signature_covers_shape_and_dtype():
    assert state_signature(_state((4, 8))) != state_signature(_state((4, 16)))
    a = {"a": np.zeros(4, np.int64)}
    b = {"a": np.zeros(4, np.int32)}
    assert state_signature(a) != state_signature(b)


# --- spec expansion -----------------------------------------------------

BASE = {
    "general": {"stop_time": "100 ms"},
    "hosts": {
        "peer": {
            "network_node_id": 0,
            "quantity": 4,
            "processes": [
                {"path": "phold", "args": {"min_delay": "2 ms", "max_delay": "9 ms"}}
            ],
        }
    },
}


def test_spec_expands_seeds_and_groups_modulo_seed(tmp_path):
    spec = load_sweep_spec(
        {
            "sweep": {
                "name": "t",
                "output_dir": str(tmp_path / "out"),
                "config": BASE,
                "jobs": [
                    {"name": "a", "seed_range": [0, 3]},
                    {"name": "b", "seeds": [5], "overrides": {
                        "experimental": {"pump_k": 4}}},
                ],
            }
        }
    )
    assert [j.name for j in spec.jobs] == ["a-s0", "a-s1", "a-s2", "b-s5"]
    groups = {j.group_key for j in spec.jobs if j.entry == "a"}
    assert len(groups) == 1  # seeds collapse to one world
    (bg,) = {j.group_key for j in spec.jobs if j.entry == "b"}
    assert bg not in groups  # the override is a different world
    # per-job configs resolved: seed and data dir are job-specific
    j = spec.jobs[1]
    assert j.config.general.seed == 1
    assert j.config.general.data_directory.endswith("jobs/a-s1")


def test_spec_rejects_replicas_duplicates_and_empty(tmp_path):
    with pytest.raises(ValueError, match="replicas"):
        load_sweep_spec(
            {
                "sweep": {
                    "config": {**BASE, "general": {"stop_time": "1 s", "replicas": 2}},
                    "jobs": [{"name": "a", "seeds": [0]}],
                }
            }
        )
    with pytest.raises(ValueError, match="duplicate seeds"):
        load_sweep_spec(
            {"sweep": {"config": BASE,
                       "jobs": [{"name": "a", "seeds": [0, 0]}]}}
        )
    with pytest.raises(ValueError, match="duplicate sweep job name"):
        load_sweep_spec(
            {"sweep": {"config": BASE,
                       "jobs": [{"name": "a", "seeds": [0]},
                                {"name": "a", "seeds": [1]}]}}
        )
    with pytest.raises(ValueError, match="jobs"):
        load_sweep_spec({"sweep": {"config": BASE, "jobs": []}})
    with pytest.raises(ValueError, match="exactly one of"):
        load_sweep_spec({"sweep": {"jobs": [{"name": "a", "seeds": [0]}]}})
    # chaos is sweep-global (one FaultPlan per sweep): a per-entry chaos
    # override would be silently ignored, so it is rejected loudly
    with pytest.raises(ValueError, match="chaos is sweep-global"):
        load_sweep_spec(
            {"sweep": {"config": BASE,
                       "jobs": [{"name": "a", "seeds": [0],
                                 "overrides": {"chaos": {
                                     "faults": [{"kind": "capacity"}]}}}]}}
        )
