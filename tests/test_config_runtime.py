"""Config parsing + Manager end-to-end runs (the analogue of the
reference's config tests, src/test/config/, and the 3-host example runs)."""

import json
import os

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.runtime.manager import Manager
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

BASIC = """
general:
  stop_time: "300 ms"
  seed: 9
  heartbeat_interval: "100 ms"
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 1 target 1 latency "1 ms" ]
        edge [ source 0 target 1 latency "5 ms" packet_loss 0.02 ]
      ]
experimental:
  scheduler: {scheduler}
  queue_capacity: 32
x-custom: ignored
hosts:
  alpha:
    network_node_id: 0
    quantity: 6
    processes:
      - path: phold
        args: {{ min_delay: "1 ms", max_delay: "10 ms" }}
  beta:
    network_node_id: 1
    quantity: 2
    ip_addr: null
    processes:
      - path: phold
        args: {{ min_delay: "1 ms", max_delay: "10 ms" }}
"""


def test_config_parsing():
    cfg = load_config_str(BASIC.format(data_dir="/tmp/x", scheduler="tpu"))
    assert cfg.general.stop_time_ns == 300 * NS_PER_MS
    assert cfg.general.seed == 9
    assert cfg.experimental.queue_capacity == 32
    assert len(cfg.hosts) == 2
    assert cfg.hosts[0].quantity == 6
    assert cfg.hosts[0].processes[0].args["min_delay"] == "1 ms"


def test_config_rejects_unknown_keys_and_missing_sections():
    with pytest.raises(ValueError):
        load_config_str("general: {stop_time: '1 s', bogus_key: 1}\nhosts: {a: {processes: [{path: phold}]}}")
    with pytest.raises(ValueError):
        load_config_str("hosts: {a: {processes: [{path: phold}]}}")  # no general
    with pytest.raises(ValueError):
        load_config_str("general: {stop_time: '1 s'}")  # no hosts
    with pytest.raises(ValueError):
        load_config_str("general: {stop_time: '0 s'}\nhosts: {a: {processes: [{path: phold}]}}")


def test_manager_end_to_end_tpu(tmp_path):
    cfg = load_config_str(BASIC.format(data_dir=tmp_path / "data", scheduler="tpu"))
    mgr = Manager(cfg)
    # expansion: alpha1..alpha6 + beta1, beta2; auto IPs from 11.0.0.0
    assert [h.name for h in mgr.hosts][:3] == ["alpha1", "alpha2", "alpha3"]
    assert mgr.ip.ip_str(0) == "11.0.0.1"
    results = mgr.run()
    assert results.events_handled > 50
    assert results.packets_unroutable == 0
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    assert stats["events_handled"] == results.events_handled
    assert stats["num_hosts"] == 8
    hosts_file = (tmp_path / "data" / "hosts").read_text().splitlines()
    assert hosts_file[0] == "11.0.0.1 alpha1"
    assert len(hosts_file) == 8
    assert (tmp_path / "data" / "processed-config.json").exists()


def test_manager_tpu_matches_cpu_ref_scheduler(tmp_path):
    cfg_t = load_config_str(BASIC.format(data_dir=tmp_path / "t", scheduler="tpu"))
    cfg_c = load_config_str(BASIC.format(data_dir=tmp_path / "c", scheduler="cpu-ref"))
    rt = Manager(cfg_t).run()
    rc = Manager(cfg_c).run()
    assert rt.events_handled == rc.events_handled
    assert rt.packets_sent == rc.packets_sent
    assert rt.packets_dropped == rc.packets_dropped


def test_example_config_runs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    path = os.path.join(os.path.dirname(__file__), "..", "examples", "phold", "shadow.yaml")
    from shadow_tpu.config import load_config_file

    cfg = load_config_file(path)
    cfg.general.stop_time_ns = 200 * NS_PER_MS  # keep the test fast
    results = Manager(cfg).run()
    assert results.events_handled > 0


def test_cli_show_config(tmp_path, capsys):
    from shadow_tpu.cli import main

    p = tmp_path / "c.yaml"
    p.write_text(BASIC.format(data_dir=tmp_path / "d", scheduler="tpu"))
    assert main(["run", str(p), "--show-config"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["general"]["seed"] == 9


SHAPED = """
general:
  stop_time: "200 ms"
  seed: 4
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "2 Mbit" host_bandwidth_down "2 Mbit" ]
        edge [ source 0 target 0 latency "1 ms" ]
      ]
hosts:
  h:
    network_node_id: 0
    quantity: 4
    processes:
      - path: phold
        args: {{ min_delay: "1 ms", max_delay: "4 ms", ball_bytes: 1400 }}
"""


def test_config_bandwidth_reaches_engine(tmp_path):
    """YAML bandwidth must shape traffic: a 2 Mbit access link caps phold's
    ball rate well below the unshaped rate (regression: config-parsed
    bandwidths were silently dropped before reaching EngineConfig)."""
    cfg = load_config_str(SHAPED.format(data_dir=tmp_path / "a"))
    res_shaped = Manager(cfg).run()

    unshaped = SHAPED.replace(' host_bandwidth_up "2 Mbit" host_bandwidth_down "2 Mbit"', "")
    cfg2 = load_config_str(unshaped.format(data_dir=tmp_path / "b"))
    res_free = Manager(cfg2).run()

    # 2 Mbit = 250 bytes/ms; a 1400-byte ball every ~2.5ms/host unshaped vs
    # ~5.6ms/ball shaped per host pair -> strictly fewer events when shaped
    assert res_shaped.events_handled < res_free.events_handled


def test_manager_rejects_differing_model_args(tmp_path):
    two_args = BASIC.format(data_dir=tmp_path / "c", scheduler="tpu").replace(
        'args: { min_delay: "1 ms", max_delay: "10 ms" }',
        'args: { min_delay: "2 ms", max_delay: "10 ms" }',
        1,
    )
    cfg = load_config_str(two_args)
    with pytest.raises(ValueError, match="identical args"):
        Manager(cfg).run()
