"""Tier-1 CLI smoke for the ensemble plane: `--replicas 2` runs end to
end and publishes per-replica + aggregate sim-stats sections; resuming a
replicated run with a mismatched replica count fails with a clear config
error (the fingerprint covers replicas/engine/tracker), never a shape
mismatch deep in jax."""

import json
import pathlib

import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.runtime.checkpoint import config_fingerprint
from shadow_tpu.runtime.cli_run import CliUserError, run_from_config

CONFIG = """
general:
  stop_time: 120 ms
  seed: {seed}
  data_directory: {data_dir}
  heartbeat_interval: null
  tracker: true
network:
  graph:
    type: 1_gbit_switch
experimental:
  rounds_per_chunk: 4
hosts:
  peer:
    network_node_id: 0
    quantity: 8
    processes:
      - path: phold
        args:
          min_delay: "2 ms"
          max_delay: "12 ms"
"""


def _write(tmp_path, name, seed=1) -> pathlib.Path:
    d = tmp_path / name
    d.mkdir()
    cfg = d / "shadow.yaml"
    cfg.write_text(CONFIG.format(data_dir=d / "data", seed=seed))
    return cfg


def _stats(cfg_path: pathlib.Path) -> dict:
    return json.loads((cfg_path.parent / "data" / "sim-stats.json").read_text())


def test_cli_replicas_end_to_end(tmp_path):
    cfg = _write(tmp_path, "ens")
    assert run_from_config(str(cfg), replicas=2, replica_seed_stride=3) == 0
    stats = _stats(cfg)
    assert stats["scheduler"] == "tpu-ensemble"
    ens = stats["ensemble"]
    assert ens["replicas"] == 2 and ens["seed_stride"] == 3
    per = ens["per_replica"]
    assert len(per) == 2
    assert [p["seed"] for p in per] == [1, 4]  # seed + r*stride
    assert all(p["events_handled"] > 0 for p in per)
    # top-level counters are the totals across replicas
    assert stats["events_handled"] == sum(p["events_handled"] for p in per)
    agg = ens["aggregate"]
    for metric in ("events_handled", "packets_sent", "bytes_sent"):
        block = agg[metric]
        assert {"mean", "stddev", "min", "max", "ci95"} <= set(block)
        assert block["min"] <= block["mean"] <= block["max"]
        lo, hi = block["ci95"]
        assert lo <= block["mean"] <= hi
    assert ens["wall_seconds_per_replica"] < ens["wall_seconds"]
    # the tracker fold still publishes (flattened across replicas)
    assert stats["tracker"]["events_by_kind"]["local"] > 0


def test_cli_replicas_resume_mismatch_fails(tmp_path, monkeypatch):
    """Satellite pin: a checkpointed 2-replica run refuses to resume as a
    3-replica run — the replica count is in the config fingerprint, so
    the failure is a one-line config error, not a jax shape explosion."""
    run_cfg = _write(tmp_path, "run")
    ckpt_dir = str(tmp_path / "ckpts")
    monkeypatch.setenv("SHADOW_TPU_TEST_INTERRUPT_AT_NS", str(60_000_000))
    rc = run_from_config(
        str(run_cfg),
        checkpoint_dir=ckpt_dir,
        checkpoint_interval="20 ms",
        replicas=2,
    )
    assert rc == 130
    assert sorted(pathlib.Path(ckpt_dir).glob("ckpt-*.npz"))
    monkeypatch.delenv("SHADOW_TPU_TEST_INTERRUPT_AT_NS")

    with pytest.raises(CliUserError, match="different config"):
        run_from_config(
            str(run_cfg), checkpoint_dir=ckpt_dir, resume=True, replicas=3
        )

    # the matching count resumes fine, bit-exact stats contract aside
    rc = run_from_config(
        str(run_cfg), checkpoint_dir=ckpt_dir, resume=True, replicas=2
    )
    assert rc == 0
    assert _stats(run_cfg)["ensemble"]["replicas"] == 2


def test_cli_replicas_rejects_parallelism(tmp_path):
    """Explicit multi-device sharding does not compose with the replica
    vmap yet: refuse loudly instead of silently running single-device."""
    cfg = _write(tmp_path, "par")
    cfg.write_text(cfg.read_text().replace("general:", "general:\n  parallelism: 4"))
    with pytest.raises(CliUserError, match="parallelism"):
        run_from_config(str(cfg), replicas=2)


def test_cli_replicas_rejects_cpu_ref(tmp_path):
    cfg = _write(tmp_path, "cpuref")
    text = cfg.read_text().replace(
        "experimental:", "experimental:\n  scheduler: cpu-ref"
    )
    cfg.write_text(text)
    with pytest.raises(CliUserError, match="replicas"):
        run_from_config(str(cfg), replicas=2)


def test_fingerprint_covers_determinism_knobs(tmp_path):
    """The config fingerprint must move with every determinism-relevant
    option (replicas, seed stride, engine, pump_k, tracker, seed) and
    stay put for display-only knobs (data_directory, progress)."""
    base_text = CONFIG.format(data_dir=tmp_path / "d", seed=1)
    base = config_fingerprint(load_config_str(base_text))

    def fp(mutate):
        c = load_config_str(base_text)
        mutate(c)
        return config_fingerprint(c)

    moved = {
        "replicas": fp(lambda c: setattr(c.general, "replicas", 2)),
        "stride": fp(lambda c: setattr(c.general, "replica_seed_stride", 5)),
        "engine": fp(lambda c: setattr(c.experimental, "engine", "plain")),
        "pump_k": fp(lambda c: setattr(c.experimental, "pump_k", 4)),
        "tracker": fp(lambda c: setattr(c.general, "tracker", False)),
        "seed": fp(lambda c: setattr(c.general, "seed", 2)),
    }
    for name, v in moved.items():
        assert v != base, f"{name} must change the fingerprint"
    assert len(set(moved.values())) == len(moved)  # and independently

    same = {
        "data_directory": fp(
            lambda c: setattr(c.general, "data_directory", "elsewhere")
        ),
        "progress": fp(lambda c: setattr(c.general, "progress", True)),
    }
    for name, v in same.items():
        assert v == base, f"{name} must NOT change the fingerprint"
