"""Syscall-breadth tests: dup2/dup3, vectored IO, msghdr IO, fstat,
lseek, identity, sysinfo, sched_yield, clock_nanosleep (reference:
handler/{unistd,uio,socket,sysinfo,sched}.rs + the dup/file paired
suites under src/test/)."""

import pathlib
import subprocess

import pytest

from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
from shadow_tpu.simtime import NS_PER_SEC

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def breadth_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "breadth_guest"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(GUESTS / "breadth_guest.c")], check=True
    )
    return str(out)


def _run(tmp_path, breadth_bin, sub="a"):
    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / sub)
    p = k.add_process(ProcessSpec(host="box", args=[breadth_bin]))
    try:
        k.run(5 * NS_PER_SEC)
    finally:
        k.shutdown()
    return k, p


def test_breadth_under_shim(tmp_path, breadth_bin):
    k, p = _run(tmp_path, breadth_bin)
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "breadth all ok" in out
    # deterministic identity
    assert "pid=1000 ppid=1 uid=1000 gid=1000" in out
    # sim uptime starts at 0 (2000-01-01 epoch)
    assert "uptime=0" in out or "uptime=1" in out
    assert k.syscall_counts["dup2"] >= 2
    assert k.syscall_counts["fstat"] >= 1


def test_breadth_deterministic(tmp_path, breadth_bin):
    a = _run(tmp_path, breadth_bin, "r1")[1].stdout()
    b = _run(tmp_path, breadth_bin, "r2")[1].stdout()
    assert a == b


@pytest.fixture(scope="module")
def breadth2_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests") / "breadth2_guest"
    subprocess.run(
        ["cc", "-O2", "-o", str(out), str(GUESTS / "breadth2_guest.c")], check=True
    )
    return str(out)


def test_breadth2_deterministic_views(tmp_path, breadth2_bin):
    """Round-2 surface: affinity, rlimits, prctl filtering, statx and
    newfstatat (incl. AT_EMPTY_PATH on virtual fds), getdents64 in the
    sandbox, pread/pwrite, sim-time process clocks, blocked-signal
    pending delivery, sendmmsg over simulated UDP."""
    k, p = _run(tmp_path, breadth2_bin)
    out = p.stdout().decode()
    assert p.exit_code == 0, out + p.stderr().decode()
    assert "breadth2 all ok" in out
    assert "FAIL" not in out
    # kernel saw the mask changes (VSYS_SIGMASK trips)
    assert k.syscall_counts.get("rt_sigprocmask", 0) >= 2


def test_breadth2_run_twice(tmp_path, breadth2_bin):
    a = _run(tmp_path, breadth2_bin, "b1")[1].stdout()
    b = _run(tmp_path, breadth2_bin, "b2")[1].stdout()
    assert a == b


def test_msg_waitall(tmp_path):
    import subprocess

    guests = pathlib.Path(__file__).parent / "guests"
    out = tmp_path / "waitall_guest"
    subprocess.run(
        ["cc", "-O2", "-pthread", "-o", str(out), str(guests / "waitall_guest.c")],
        check=True,
    )
    # native pairing
    r = subprocess.run([str(out)], capture_output=True, text=True, cwd=tmp_path)
    assert r.returncode == 0 and "waitall ok" in r.stdout, r.stdout + r.stderr

    from shadow_tpu.graph import NetworkGraph

    graph = NetworkGraph.from_gml(
        'graph [\n  node [ id 0 ]\n  edge [ source 0 target 0 latency "1 ms" ]\n]'
    )
    tables = compute_routing(graph).with_hosts([0])
    k = NetKernel(tables, host_names=["box"], host_nodes=[0], data_dir=tmp_path / "d")
    p = k.add_process(ProcessSpec(host="box", args=[str(out)]))
    try:
        k.run(5 * NS_PER_SEC)
    finally:
        k.shutdown()
    assert p.exit_code == 0, p.stdout() + p.stderr()
    assert b"waitall ok" in p.stdout()
