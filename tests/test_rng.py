import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu import rng


def test_per_host_streams_are_independent_and_deterministic():
    keys = rng.host_keys(1234, 8)
    c0 = jnp.zeros((8,), jnp.uint32)
    u1 = np.asarray(rng.uniform_f32(keys, c0))
    u2 = np.asarray(rng.uniform_f32(keys, c0))
    np.testing.assert_array_equal(u1, u2)  # same (host, counter) -> same draw
    u3 = np.asarray(rng.uniform_f32(keys, c0 + 1))
    assert not np.array_equal(u1, u3)  # next counter -> different draw
    assert len(set(u1.tolist())) == 8  # hosts differ
    assert (u1 >= 0).all() and (u1 < 1).all()


def test_seed_changes_everything():
    a = np.asarray(rng.uniform_f32(rng.host_keys(1, 4), jnp.zeros((4,), jnp.uint32)))
    b = np.asarray(rng.uniform_f32(rng.host_keys(2, 4), jnp.zeros((4,), jnp.uint32)))
    assert not np.array_equal(a, b)


def test_uniform_int_bounds_and_scalar_vs_vector_draws():
    keys = rng.host_keys(7, 16)
    c = jnp.arange(16, dtype=jnp.uint32)
    v = np.asarray(rng.uniform_int(keys, c, 5, 15))
    assert ((v >= 5) & (v < 15)).all()
    # a single host's draw must not depend on the batch it was drawn in
    solo = np.asarray(rng.uniform_int(keys[3:4], c[3:4], 5, 15))
    assert solo[0] == v[3]


def test_bernoulli_rate():
    keys = rng.host_keys(99, 4096)
    c = jnp.zeros((4096,), jnp.uint32)
    hits = np.asarray(rng.bernoulli(keys, c, jnp.float32(0.25))).mean()
    assert 0.2 < hits < 0.3


def test_exponential_positive_and_mean():
    keys = rng.host_keys(5, 4096)
    c = jnp.zeros((4096,), jnp.uint32)
    d = np.asarray(rng.exponential_ns(keys, c, 1_000_000))
    assert (d >= 0).all()
    assert 0.8e6 < d.mean() < 1.2e6


def test_replica_keys_no_collisions_and_match_host_keys():
    """The ensemble plane's independence claim (engine/ensemble.py) rests
    on two properties of the replica key grid: row r is EXACTLY the key
    set a single run with the derived seed would build, and no key
    repeats anywhere across replicas x hosts."""
    import jax

    base, R, H, stride = 1234, 16, 64, 3
    grid = rng.replica_keys(base, R, H, stride=stride)
    assert grid.shape == (R, H)
    # row r == host_keys(base + r*stride): the derived-seed contract
    for r in (0, 1, R - 1):
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(grid[r])),
            np.asarray(jax.random.key_data(rng.host_keys(base + r * stride, H))),
        )
    # no collisions across the full R x H grid (raw key words unique)
    words = np.asarray(jax.random.key_data(grid)).reshape(R * H, -1)
    assert len({tuple(w) for w in words}) == R * H
    # overlapping strides stay collision-free too (seeds differ -> roots
    # differ): replicas of (base, stride=1) vs (base+1, stride=1) share
    # derived seeds ONLY where the integers collide — guard the guard:
    with pytest.raises(ValueError, match="stride"):
        rng.replica_keys(base, 2, 4, stride=0)


def test_uniform_block_matches_uniform_f32():
    """The managed kernel's batched draws must stay bit-identical to the
    device engine's per-counter uniforms (shared determinism contract)."""
    import jax.numpy as jnp
    import numpy as np

    from shadow_tpu import rng

    keys = rng.host_keys(seed=5, num_hosts=3)
    for h in range(3):
        for start in (0, 7, 1000):
            block = np.asarray(rng.uniform_block(keys[h], jnp.uint32(start), 16))
            singles = np.asarray(
                rng.uniform_f32(
                    jnp.repeat(keys[h : h + 1], 16, axis=0),
                    jnp.arange(start, start + 16, dtype=jnp.uint32),
                )
            )
            assert (block == singles).all()
