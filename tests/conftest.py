"""Test harness: run everything on a virtual 8-device CPU mesh.

Two jobs, both of which must happen before jax backends initialize:

1. Force the CPU platform with 8 virtual devices (multi-chip sharding tests).
2. Neutralize the axon TPU plugin. The machine image injects an axon PJRT
   plugin via PYTHONPATH sitecustomize which registers itself at interpreter
   startup and dials a local relay at first backend init; when that relay is
   down, backend init hangs forever — even under JAX_PLATFORMS=cpu. The
   plugin is already registered by the time pytest imports this conftest, so
   we drop its factory from jax's backend registry before any array op.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax already (baking jax_platforms=axon from
# the env), so the env var alone is not enough:
jax.config.update("jax_platforms", "cpu")

# Pallas must import while the "tpu" platform is still registered (its
# checkify import registers a tpu lowering rule and dies on an unknown
# platform) — so pull it in BEFORE dropping the backend factories. The
# engine's megakernel (engine/megakernel.py) then imports it freely.
import jax.experimental.pallas  # noqa: E402,F401

try:  # jax-internal, but the only seam that works post-registration
    from jax._src import xla_bridge as _xb

    for _name in ("axon", "tpu"):
        _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover — registry layout changed; rely on env
    pass

import pytest  # noqa: E402

import shadow_tpu  # noqa: E402,F401  (enables x64)

# ---- quick/full suite tiers --------------------------------------------
# The always-green quick tier is `pytest -m "not slow"` (~5 min on the
# 1-core CI box); the full suite (~24 min) runs everything. Tests whose
# measured wall time exceeds ~8 s are marked slow by base name, so new
# parametrizations of a slow test inherit the marker. Re-derive the list
# with `pytest --durations=0` when timings drift.
SLOW_TESTS = {
    "test_client_reaches_closed_after_timewait",
    "test_config_bandwidth_reaches_engine",
    "test_determinism_two_runs_identical",
    "test_device_tcp_matches_scalar_oracle",
    "test_device_tgen_matches_scalar_oracle",
    "test_dynamic_matches_static_results",
    "test_dynamic_window_covers_more_time",
    "test_engine_matches_cpu_reference",
    "test_engine_netstack_matches_cpu_reference",
    "test_example_config_runs",
    "test_fattree_bulk_tcp_smoke",
    "test_goodput_tracks_bandwidth_cap",
    "test_handshake_and_transfer_no_loss",
    "test_http_example",
    "test_http_matrix_104_hosts",
    "test_hybrid_run_twice_deterministic",
    "test_manager_end_to_end_tpu",
    "test_manager_tpu_matches_cpu_ref_scheduler",
    "test_many_pairs_all_complete",
    "test_many_to_few_servers",
    "test_netstack_jit_matches_debug_and_shapes_traffic",
    "test_parallel_matches_serial",
    "test_parallel_worker_count_invariant",
    "test_phold_compact_bit_identical",
    "test_python_http_server_serves_curl",
    "test_python_http_server_deterministic",
    "test_sharded_bulk_tcp_1k_hosts_matches_single",
    "test_sharded_compact_matches_single_device",
    "test_sharded_matches_single_device",
    "test_streams_cycle",
    "test_streams_deterministic",
    "test_system_curl_run_twice_strace_identical",
    "test_tgen_compact_bit_identical",
    "test_transfer_completes_under_loss",
    "test_unmatched_segment_draws_rst",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.default_backend()})"
