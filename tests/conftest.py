"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set env vars before jax initializes its backends, so this executes at
conftest import time (pytest loads conftest before test modules).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

import shadow_tpu  # noqa: E402,F401  (enables x64)


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.default_backend()})"
