"""Test harness: run everything on a virtual 8-device CPU mesh.

Two jobs, both of which must happen before jax backends initialize:

1. Force the CPU platform with 8 virtual devices (multi-chip sharding tests).
2. Neutralize the axon TPU plugin. The machine image injects an axon PJRT
   plugin via PYTHONPATH sitecustomize which registers itself at interpreter
   startup and dials a local relay at first backend init; when that relay is
   down, backend init hangs forever — even under JAX_PLATFORMS=cpu. The
   plugin is already registered by the time pytest imports this conftest, so
   we drop its factory from jax's backend registry before any array op.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax already (baking jax_platforms=axon from
# the env), so the env var alone is not enough:
jax.config.update("jax_platforms", "cpu")

# Pallas must import while the "tpu" platform is still registered (its
# checkify import registers a tpu lowering rule and dies on an unknown
# platform) — so pull it in BEFORE dropping the backend factories. The
# engine's megakernel (engine/megakernel.py) then imports it freely.
import jax.experimental.pallas  # noqa: E402,F401

try:  # jax-internal, but the only seam that works post-registration
    from jax._src import xla_bridge as _xb

    for _name in ("axon", "tpu"):
        _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover — registry layout changed; rely on env
    pass

import pytest  # noqa: E402

import shadow_tpu  # noqa: E402,F401  (enables x64)

# ---- quick/full suite tiers --------------------------------------------
# The always-green quick tier is `pytest -m "not slow"` (~5 min on the
# 1-core CI box); the full suite (~24 min) runs everything. Tests whose
# measured wall time exceeds ~8 s are marked slow by base name, so new
# parametrizations of a slow test inherit the marker. Re-derive the list
# with `pytest --durations=0` when timings drift.
SLOW_TESTS = {
    "test_client_reaches_closed_after_timewait",
    "test_config_bandwidth_reaches_engine",
    "test_determinism_two_runs_identical",
    "test_device_tcp_matches_scalar_oracle",
    "test_ensemble_matches_single_tgen",
    "test_ensemble_checkpoint_resume_exact",
    "test_ensemble_checkpoint_straddling_quiescence_exact",
    "test_ensemble_recovery_regrows_whole_batch",
    "test_ensemble_pipelined_matches_sync",
    "test_device_tgen_matches_scalar_oracle",
    "test_dynamic_matches_static_results",
    "test_dynamic_window_covers_more_time",
    "test_engine_matches_cpu_reference",
    "test_engine_netstack_matches_cpu_reference",
    "test_example_config_runs",
    "test_fattree_bulk_tcp_smoke",
    "test_goodput_tracks_bandwidth_cap",
    "test_handshake_and_transfer_no_loss",
    "test_http_example",
    "test_http_matrix_104_hosts",
    "test_hybrid_run_twice_deterministic",
    "test_manager_end_to_end_tpu",
    "test_manager_tpu_matches_cpu_ref_scheduler",
    "test_many_pairs_all_complete",
    "test_many_to_few_servers",
    "test_netstack_jit_matches_debug_and_shapes_traffic",
    "test_parallel_matches_serial",
    "test_parallel_worker_count_invariant",
    "test_phold_compact_bit_identical",
    "test_python_http_server_serves_curl",
    "test_python_http_server_deterministic",
    "test_sharded_bulk_tcp_1k_hosts_matches_single",
    "test_sharded_compact_matches_single_device",
    "test_sharded_matches_single_device",
    # Mesh-round budget split (tests/test_mesh.py + the daemon
    # compaction pin): the tier-1 suite ran 782s of its 870s cap before
    # this round, so the quick tier takes only the acceptance pins —
    # phold slice equivalence, mesh checkpoint/resume, the (replica,
    # shard) capacity naming, plan/spec validation, and the 4-job
    # one-compile sweep smoke (~60s together). The full-stack tgen slice
    # pin (~4 min shard_map compile), the whole-batch regrow pin
    # (mirroring its already-slow ensemble counterpart), and the
    # kill-during-compaction daemon pin (subprocess daemons) run in the
    # full tier.
    "test_mesh_slice_matches_single_tgen_pump",
    "test_mesh_recovery_regrows_whole_batch",
    "test_daemon_journal_compaction_survives_kill",
    # Elastic-mesh round budget split (tests/test_elastic.py,
    # tests/test_elastic_cli.py): the quick tier keeps the acceptance
    # pins — the 2x4-checkpoint-resumes-anywhere CLI matrix, the
    # device-loss CLI completion, the degraded-grid capacity naming,
    # the terminal-outside-mesh pin, and the pure units (~60s). The
    # engine-level leaf-exact replay pin, the regrow-on-degraded-grid
    # pin, and the sweep-batch survival pin each pay extra mesh
    # compiles (~20 s apiece) and run in the full tier.
    "test_device_loss_degrades_mesh_and_replays_leaf_exact",
    "test_whole_batch_regrow_on_grid_reached_via_degradation",
    "test_sweep_batch_survives_device_loss",
    "test_device_loss_terminal_outside_mesh_is_structured",
    "test_capacity_naming_on_grid_reached_via_degradation",
    # Elastic-round REBALANCE: the quick tier measured 1080s on this
    # box (the 870s cap was already breached before this round's ~60s
    # of acceptance pins — the 782s PR-14 number was a faster day).
    # Moved to the full tier, each with quick-tier coverage of the same
    # plane retained: the shaped pump-vs-plain tgen matrix (~122s —
    # test_pump_unshaped_world_matches still pins pump-tgen equivalence
    # quick), the pump-tgen tracker cross-engine cell (~80s — the phold
    # trajectory pin, probe-lane, fold and CLI tracker tests stay
    # quick), the onion example ensemble rung (~62s — the registry
    # [onion] smoke and the single-run example stay), and the
    # netstack-noop equivalence (~30s — bootstrap-period shaping and
    # the TCP suites keep quick netstack coverage).
    "test_pump_bit_identical_tgen",
    "test_tracker_counters_cross_engine_pump_tgen",
    "test_onion_example_replicas_aggregate",
    "test_netstack_unlimited_is_noop",
    # ~103s: the forced-CPU bench harness subprocess canary — the
    # biggest single quick-tier item after the rebalance and a harness
    # smoke rather than a correctness pin; the capped rerun still
    # landed only ~30s under the 870s wall, so it funds the margin
    "test_bench_cpu_rung_publishes_non_null",
    "test_streams_cycle",
    "test_streams_deterministic",
    "test_system_curl_run_twice_strace_identical",
    "test_tgen_compact_bit_identical",
    "test_transfer_completes_under_loss",
    "test_unmatched_segment_draws_rst",
    # ~38 s solo (two end-to-end 64 MB managed-guest runs); under
    # full-suite contention the guests' syscall waits flake on wall time
    # (CHANGES.md PR 8) — the structural work-ratio assertions inside it
    # are contention-proof, the wall is not, so it runs in the full tier
    "test_bulk_pipe_stream_integrity_and_speed",
    # the adaptive-window equivalence MATRIX (engines x tgen, sharded,
    # ensemble) pays an XLA compile per cell (~40-90 s each on this box);
    # the quick tier keeps the tentpole pins (phold leaf-exactness +
    # iteration reduction, checkpoint roundtrip, the bench smoke)
    "test_adaptive_matches_fixed_tgen_engines",
    "test_adaptive_matches_fixed_sharded",
    "test_adaptive_matches_fixed_ensemble_slices",
    # Event-exchange v2 (tests/test_exchange.py): the quick tier keeps
    # one dense-vs-segment phold smoke per engine plus the pure
    # pool/ergonomics pins (~50s); the full 6-model x 3-engine matrix
    # (an XLA compile per cell), the ensemble/mesh slice cells, and the
    # segment chaos-recovery pin run in the full tier
    "test_segment_matches_dense_matrix",
    "test_ensemble_segment_slices_exact",
    "test_mesh_segment_slices_match_single_dense",
    "test_segment_chaos_capacity_recovers_leaf_exact",
    # ~25 s; the quick tier already runs the real checkpoint machinery
    # with adaptive windows on by default (tests/test_robustness.py)
    "test_adaptive_checkpoint_roundtrip_leaf_exact",
    # overlay equivalence matrix (tests/test_overlay.py): each cell pays
    # an onion/cdn/gossip XLA compile (the onion handler is tgen-class);
    # the quick tier keeps the registry smoke (one compile per model)
    # and the example CLI smoke
    "test_onion_pump_matches_plain",
    "test_overlay_ensemble_slices_exact",
    "test_onion_chaos_capacity_recovers_leaf_exact",
    "test_onion_circuits_streams_and_scheduling",
    "test_cdn_hierarchy_fills_downward",
    "test_gossip_churn_and_view_mixing",
    # one compile per example rung is enough for the quick tier: it
    # keeps the --replicas 2 CLI smoke (the satellite contract — the
    # ensemble path subsumes the single-run plumbing), the single-run
    # rung joins the full tier
    "test_onion_example_runs",
}


# ---- managed-guest (LD_PRELOAD shim) availability ----------------------
# The hostk/hybrid/managed suites run real executables under the
# LD_PRELOAD shim. In some container images the shim cannot load into
# guests at all (observed here: `symbol lookup error: libshadow_shim.so:
# undefined symbol: dlsym` — a glibc linking mismatch — so every guest
# exits 127; the seed suites fail there pre-existing, CHANGES.md PR 4).
# Probe ONCE per session — compile a trivial guest and run it under a
# minimal NetKernel in a subprocess (a subprocess so a hung guest cannot
# wedge collection) — and when the probe fails, auto-skip the
# guest-execution tests with the probe's reason instead of failing them
# one by one. Engine-level suites never skip.

_GUEST_PROBE_SCRIPT = r"""
import pathlib, subprocess, sys, tempfile
root = pathlib.Path(sys.argv[1])
sys.path.insert(0, str(root))
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.hostk.kernel import NetKernel, ProcessSpec
tmp = pathlib.Path(tempfile.mkdtemp(prefix="shim-probe-"))
src = tmp / "guest.c"
src.write_text("int main(void) { return 0; }\n")
exe = tmp / "guest"
subprocess.run(["cc", "-O0", "-o", str(exe), str(src)], check=True)
graph = NetworkGraph.from_gml(
    'graph [ directed 0 node [ id 0 ] '
    'edge [ source 0 target 0 latency "1 ms" ] ]'
)
tables = compute_routing(graph).with_hosts([0])
k = NetKernel(tables, host_names=["h"], host_nodes=[0], seed=1,
              data_dir=tmp / "data")
p = k.add_process(ProcessSpec(host="h", args=[str(exe)]))
try:
    k.run(1_000_000_000)
finally:
    k.shutdown()
print("GUEST_OK" if p.exit_code == 0
      else f"GUEST_BAD: trivial guest exited {p.exit_code} "
           f"(state {p.state}) under the shim")
"""

# The seed tests that REQUIRE working guest execution (real binaries
# under the shim — directly, via the hybrid scheduler, or via the
# managed CLI): exactly these skip when the probe fails. Their modules
# also hold engine-level and native-guest tests that pass without the
# shim, which is why this is a test list, not a module list.
GUEST_EXEC_TESTS = {
    "test_cli_managed_end_to_end",
    "test_cli_serial_scheduler_matches_hybrid",
    "test_cli_double_run_strace_identical",
    "test_cli_managed_shutdown_while_blocked",
    "test_cli_expected_running_killed_at_stop",
    "test_udp_echo_under_simulated_network",
    "test_exit_codes_reaped",
    "test_breadth_under_shim",
    "test_breadth2_deterministic_views",
    "test_msg_waitall",
    "test_cpp_guest_under_shim",
    "test_dns_apis_under_shim",
    "test_fd_guest_matches_native",
    "test_descriptor_families",
    "test_file_sandbox_and_virtual_devices",
    "test_urandom_deterministic_per_seed",
    "test_random_deterministic_per_seed",
    "test_fork_guest_under_shim",
    "test_forking_server_serves_three_curls",
    "test_forking_server_deterministic",
    "test_fs_breadth_values",
    "test_raw_futex_semantics",
    "test_go_patterns",
    "test_mm_guest_matches_native",
    "test_mm_ledger_tracks_guest_mappings",
    "test_fifo_keeps_burst_order",
    "test_rr_interleaves_sockets",
    "test_rr_deterministic",
    "test_raw_clone_thread_adopted",
    "test_raw_clone_slot_reuse",
    "test_raw_syscalls_intercepted",
    "test_unshaped_blast_arrives_at_line_rate",
    "test_sender_bandwidth_paces_the_burst",
    "test_receiver_bandwidth_paces_the_burst",
    "test_tcp_bulk_over_shaped_link",
    "test_signals_guest_native",
    "test_signals_guest_under_shim",
    "test_cross_process_kill",
    "test_default_disposition_terminates",
    "test_shutdown_time_uses_sigterm",
    "test_tcp_echo_small",
    "test_tcp_bulk_transfer",
    "test_tcp_retransmission_under_loss",
    "test_tcp_connection_refused",
    "test_pcap_capture",
    "test_tcp_strace_written",
    "test_threads_guest_under_shim",
    "test_main_pthread_exit_workers_continue",
    "test_rdtsc_serves_sim_time",
    "test_unix_guest_native",
    "test_unix_guest_under_shim",
    "test_unix_echo_two_processes_same_host",
    "test_hybrid_matches_serial_tcp",
    "test_hybrid_matches_serial_tcp_under_loss",
    "test_system_curl_fetches_in_sim",
    "test_system_wget_fetches_in_sim",
    "test_system_curl_sees_simulated_time",
    "test_sack_fewer_retransmits_equal_goodput",
    "test_autotune_tracks_bdp",
}


def _managed_guest_reason():
    """None when managed guests work here; else a short skip reason.
    Called at most once per session (pytest_collection_modifyitems)."""
    import subprocess
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # neutralize the axon plugin for the probe child the way bench.py's
    # _cpu_env does: the sitecustomize injection hangs backend init when
    # the relay is down, and the child has no conftest to drop it
    env.update(PYTHONPATH="", JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        r = subprocess.run(
            [_sys.executable, "-c", _GUEST_PROBE_SCRIPT, root],
            capture_output=True,
            text=True,
            timeout=180,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return "managed-guest probe hung (>180s): guest never completed"
    if "GUEST_OK" in r.stdout:
        return None
    bad = [ln for ln in r.stdout.splitlines() if ln.startswith("GUEST_BAD")]
    tail = bad or (r.stdout + r.stderr).strip().splitlines()
    detail = tail[-1][:200] if tail else f"rc={r.returncode}"
    return (
        "managed-guest (LD_PRELOAD shim) execution does not work in this "
        f"environment: {detail}"
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
    guest_items = [
        i for i in items if item_base_name(i) in GUEST_EXEC_TESTS
    ]
    if guest_items:
        reason = _managed_guest_reason()
        if reason is not None:
            marker = pytest.mark.skip(reason=reason)
            for item in guest_items:
                item.add_marker(marker)


def item_base_name(item) -> str:
    return item.name.split("[")[0]


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.default_backend()})"
