"""Test harness: run everything on a virtual 8-device CPU mesh.

Two jobs, both of which must happen before jax backends initialize:

1. Force the CPU platform with 8 virtual devices (multi-chip sharding tests).
2. Neutralize the axon TPU plugin. The machine image injects an axon PJRT
   plugin via PYTHONPATH sitecustomize which registers itself at interpreter
   startup and dials a local relay at first backend init; when that relay is
   down, backend init hangs forever — even under JAX_PLATFORMS=cpu. The
   plugin is already registered by the time pytest imports this conftest, so
   we drop its factory from jax's backend registry before any array op.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax already (baking jax_platforms=axon from
# the env), so the env var alone is not enough:
jax.config.update("jax_platforms", "cpu")

try:  # jax-internal, but the only seam that works post-registration
    from jax._src import xla_bridge as _xb

    for _name in ("axon", "tpu"):
        _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover — registry layout changed; rely on env
    pass

import shadow_tpu  # noqa: E402,F401  (enables x64)


def pytest_report_header(config):
    return f"jax {jax.__version__}, devices: {jax.device_count()} ({jax.default_backend()})"
