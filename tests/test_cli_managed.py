"""End-to-end: `shadow-tpu run config.yaml` with real executables as
managed processes (the reference's primary usage, e.g.
examples/http-server/shadow.yaml → run_shadow → Manager spawning managed
processes; reference src/main/core/main.rs:61, manager.rs:227)."""

import json
import pathlib
import subprocess

import pytest

from shadow_tpu.runtime.cli_run import CliUserError, run_from_config

GUESTS = pathlib.Path(__file__).parent / "guests"


@pytest.fixture(scope="module")
def guest_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    bins = {}
    for name in ("udp_echo", "udp_client"):
        dst = out / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True)
        bins[name] = str(dst)
    return bins


CONFIG = """
general:
  stop_time: 5 sec
  seed: 1
  data_directory: {data_dir}
  heartbeat_interval: 1 sec
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 ]
        node [ id 1 ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 1 target 1 latency "1 ms" ]
        edge [ source 0 target 1 latency "10 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {server_bin}
        args: 7000 3
        expected_final_state: exited
  client:
    network_node_id: 1
    processes:
      - path: {client_bin}
        args: [11.0.0.1, "7000", "3", "5"]
        start_time: 100 ms
        environment:
          GUEST_MARKER: hello
"""


def _write_config(tmp_path, guest_bins) -> pathlib.Path:
    cfg = tmp_path / "shadow.yaml"
    cfg.write_text(
        CONFIG.format(
            data_dir=tmp_path / "data",
            server_bin=guest_bins["udp_echo"],
            client_bin=guest_bins["udp_client"],
        )
    )
    return cfg


def test_cli_managed_end_to_end(tmp_path, guest_bins):
    cfg = _write_config(tmp_path, guest_bins)
    assert run_from_config(str(cfg)) == 0

    data = tmp_path / "data"
    stats = json.loads((data / "sim-stats.json").read_text())
    # managed configs default to the hybrid scheduler: guests on the CPU
    # kernel, packets on the device engine
    assert stats["scheduler"] == "tpu-hybrid"
    assert stats["syscalls_handled"] > 0
    assert stats["syscall_counts"]["sendto"] >= 3
    assert stats["packets_sent"] >= 6  # 3 pings + 3 echoes

    # client saw ~20ms RTTs on simulated time
    out = (data / "client" / "udp_client.1001.stdout").read_bytes().decode()
    assert out.count("rtt") == 3
    for line in out.splitlines():
        if line.startswith("rtt"):
            rtt = int(line.split()[2])
            assert 19_000_000 <= rtt <= 40_000_000

    # strace files written for both processes (standard mode default)
    assert (data / "server" / "udp_echo.1000.strace").exists()
    assert (data / "client" / "udp_client.1001.strace").exists()
    # hosts file exported (dns.c:115 analogue)
    hosts = (data / "hosts").read_text()
    assert "11.0.0.1 server" in hosts and "11.0.0.2 client" in hosts


def test_cli_serial_scheduler_matches_hybrid(tmp_path, guest_bins):
    """experimental.scheduler: managed keeps everything on the serial CPU
    kernel; guest-visible output must match the hybrid default exactly
    (same clamp grid, same threefry streams)."""
    outs = []
    for run, extra in (("hy", ""), ("se", "experimental:\n  scheduler: managed\n")):
        d = tmp_path / run
        d.mkdir()
        cfg = d / "shadow.yaml"
        cfg.write_text(
            CONFIG.format(
                data_dir=d / "data",
                server_bin=guest_bins["udp_echo"],
                client_bin=guest_bins["udp_client"],
            )
            + extra
        )
        assert run_from_config(str(cfg)) == 0
        data = d / "data"
        stats = json.loads((data / "sim-stats.json").read_text())
        outs.append(
            (
                (data / "client" / "udp_client.1001.stdout").read_bytes(),
                stats["packets_sent"],
                stats["syscall_counts"],
                stats["scheduler"],
            )
        )
    assert outs[0][0] == outs[1][0]
    assert outs[0][1] == outs[1][1]
    assert outs[0][2] == outs[1][2]
    assert (outs[0][3], outs[1][3]) == ("tpu-hybrid", "managed")


def test_cli_double_run_strace_identical(tmp_path, guest_bins):
    """The reference's determinism suite runs the same config twice with
    deterministic strace mode and diffs the outputs
    (src/test/determinism/CMakeLists.txt:1-40, determinism1_compare.cmake).
    Here: full CLI path, byte-identical strace files + stdout + stats."""
    outs = []
    for run in ("run1", "run2"):
        d = tmp_path / run
        cfg = d / "shadow.yaml"
        d.mkdir()
        cfg.write_text(
            CONFIG.format(
                data_dir=d / "data",
                server_bin=guest_bins["udp_echo"],
                client_bin=guest_bins["udp_client"],
            )
            + "experimental:\n  strace_logging_mode: deterministic\n"
        )
        assert run_from_config(str(cfg)) == 0
        data = d / "data"
        files = {}
        for p in sorted(data.rglob("*")):
            if p.suffix in (".strace", ".stdout") or p.name == "hosts":
                files[str(p.relative_to(data))] = p.read_bytes()
        stats = json.loads((data / "sim-stats.json").read_text())
        stats.pop("wall_seconds")
        files["sim-stats"] = json.dumps(stats, sort_keys=True)
        outs.append(files)
    assert outs[0].keys() == outs[1].keys()
    for name in outs[0]:
        assert outs[0][name] == outs[1][name], f"run-twice diff in {name}"


SHUTDOWN_CONFIG = """
general:
  stop_time: 5 sec
  data_directory: {data_dir}
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {server_bin}
        args: 7000 9999
        shutdown_time: 2 sec
"""


def test_cli_managed_shutdown_while_blocked(tmp_path, guest_bins):
    """A process parked in recvfrom at its shutdown_time must be torn down
    without firing its pending wakeups (reference: shutdown_signal at
    shutdown_time, configuration.rs:560-640)."""
    cfg = tmp_path / "shutdown.yaml"
    cfg.write_text(
        SHUTDOWN_CONFIG.format(data_dir=tmp_path / "data", server_bin=guest_bins["udp_echo"])
    )
    assert run_from_config(str(cfg)) == 0
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    assert stats["syscall_counts"]["recvfrom"] >= 1


def test_cli_expected_running_killed_at_stop(tmp_path, guest_bins):
    """A process configured with expected_final_state: running that is still
    alive at stop_time is killed by shadow itself — that is the *expected*
    outcome and must not fail the run (reference process.rs:1215 maps
    ExitStatus::StoppedByShadow to ProcessFinalState::Running)."""
    cfg = tmp_path / "running.yaml"
    cfg.write_text(
        """
general: {{ stop_time: 2 sec, data_directory: {d} }}
hosts:
  server:
    network_node_id: 0
    processes:
      - path: {b}
        args: 7000 9999
        expected_final_state: running
""".format(d=tmp_path / "data", b=guest_bins["udp_echo"])
    )
    assert run_from_config(str(cfg)) == 0
    stats = json.loads((tmp_path / "data" / "sim-stats.json").read_text())
    assert stats["unexpected_final_states"] == []


def test_cli_managed_mapping_args_rejected(tmp_path, guest_bins):
    cfg = tmp_path / "maparg.yaml"
    cfg.write_text(
        """
general: {{ stop_time: 1 sec, data_directory: {d} }}
hosts:
  h:
    network_node_id: 0
    processes:
      - path: {b}
        args: {{ port: 7000 }}
""".format(d=tmp_path / "data", b=guest_bins["udp_echo"])
    )
    with pytest.raises(CliUserError, match="args as a string or list"):
        run_from_config(str(cfg))


def test_cli_managed_bad_path(tmp_path, guest_bins):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text(
        CONFIG.format(
            data_dir=tmp_path / "data",
            server_bin="/nonexistent/binary",
            client_bin=guest_bins["udp_client"],
        )
    )
    with pytest.raises(CliUserError, match="neither a registered model nor an executable"):
        run_from_config(str(cfg))
