"""Hybrid worker supervision (docs/robustness.md): a worker process that
dies (or hangs) mid-run is detected by the bounded per-RPC recv, killed,
respawned, and replayed to the last round boundary — and the run's
outcomes are identical to one where nothing died (guest re-execution is
deterministic, the same contract the run-twice determinism tests pin).
Teardown must reap dead workers instead of hanging on their pipes."""

import pathlib
import subprocess
import time

import pytest

from shadow_tpu.engine import EngineConfig
from shadow_tpu.graph import compute_routing
from shadow_tpu.hostk.kernel import ProcessSpec
from shadow_tpu.runtime.hybrid import ParallelHybridScheduler, WorkerCrashed
from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC
from tests.topo import two_node_graph

GUESTS = pathlib.Path(__file__).parent / "guests"
W = 1 * NS_PER_MS


@pytest.fixture(scope="module")
def bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("guests")
    built = {}
    for name in ("tcp_echo_server", "tcp_client"):
        dst = out / name
        subprocess.run(
            ["cc", "-O2", "-o", str(dst), str(GUESTS / f"{name}.c")], check=True
        )
        built[name] = str(dst)
    return built


def _world():
    graph = two_node_graph(10, 0.0)
    host_names = ["server0", "client0"]
    host_nodes = [0, 1]
    tables = compute_routing(graph).with_hosts(host_nodes)
    cfg = EngineConfig(
        num_hosts=2, queue_capacity=256, outbox_capacity=64,
        runahead_ns=W, seed=5,
    )
    return tables, cfg, host_names, host_nodes


def _specs(bins, nbytes):
    return [
        ProcessSpec(host="server0", args=[bins["tcp_echo_server"], "8080", "1"]),
        ProcessSpec(
            host="client0",
            args=[bins["tcp_client"], "server0", "8080", str(nbytes)],
            start_ns=100 * NS_PER_MS,
        ),
    ]


class _KillableSched(ParallelHybridScheduler):
    """Test harness: SIGKILLs a chosen worker right before the Nth window
    broadcast — a deterministic stand-in for a worker crashing mid-run."""

    kill_worker: "int | None" = None
    kill_at_call = 0
    _calls = 0

    def _run_windows(self, end_ns, inclusive):
        type(self)._calls += 1
        if self.kill_worker is not None and type(self)._calls == self.kill_at_call:
            self._workers[self.kill_worker][0].kill()
            time.sleep(0.3)  # let the pipe actually close
        return super()._run_windows(end_ns, inclusive)


def _run(tmp_path, bins, name, kill_worker=None, kill_at_call=0, **kw):
    tables, cfg, host_names, host_nodes = _world()

    class Sched(_KillableSched):
        pass

    Sched.kill_worker = kill_worker
    Sched.kill_at_call = kill_at_call
    Sched._calls = 0
    sched = Sched(
        tables, cfg, host_names=host_names, host_nodes=host_nodes,
        specs=_specs(bins, 6000), num_workers=2, seed=5,
        data_dir=tmp_path / name, **kw,
    )
    try:
        try:
            sched.run(30 * NS_PER_SEC)
        finally:
            sched.shutdown()
        stats = sched.stats()
        log = sorted(sched.event_log())
        info = {
            p["host"]: (p["stdout"], p["exit_code"], p["syscalls"])
            for p in sched.proc_info()
        }
        return stats, log, info, list(sched._respawns)
    finally:
        sched.close()


def test_kill_one_worker_recovers_identically(tmp_path, bins):
    """SIGKILL one worker mid-run: the scheduler respawns it, replays its
    command log to the last round boundary, and finishes with stats,
    event log, and guest outputs identical to an undisturbed run."""
    clean = _run(tmp_path, bins, "clean")
    assert clean[3] == [0, 0]
    killed = _run(tmp_path, bins, "killed", kill_worker=1, kill_at_call=2)
    assert killed[3] == [0, 1]  # exactly one respawn, of the killed worker
    assert killed[0] == clean[0]
    assert killed[1] == clean[1]
    assert killed[2] == clean[2]


def test_respawn_budget_exhausted_raises(tmp_path, bins):
    """max_worker_respawns=0 turns a worker death into a loud
    WorkerCrashed instead of a silent infinite respawn loop."""
    with pytest.raises(WorkerCrashed, match="respawn budget"):
        _run(
            tmp_path, bins, "budget",
            kill_worker=1, kill_at_call=2, max_worker_respawns=0,
        )


def test_close_reaps_dead_worker(tmp_path, bins):
    """close() must return promptly and reap every worker process even
    when one died mid-RPC — today's bound is poll+timeout per pipe, so a
    dead worker can no longer hang the manager."""
    tables, cfg, host_names, host_nodes = _world()
    sched = ParallelHybridScheduler(
        tables, cfg, host_names=host_names, host_nodes=host_nodes,
        specs=_specs(bins, 1000), num_workers=2, seed=5,
        data_dir=tmp_path / "reap",
    )
    procs = [p for p, _c in sched._workers]
    procs[0].kill()
    time.sleep(0.3)
    t0 = time.monotonic()
    sched.close()
    assert time.monotonic() - t0 < 30
    for p in procs:
        assert not p.is_alive()
