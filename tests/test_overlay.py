"""Overlay workload pack (models/overlay/, docs/models.md): the slow-tier
equivalence matrix + behavior pins for onion / cdn / gossip.

Contracts pinned here, mirroring tests/test_ensemble.py:

  * plain-vs-pump leaf-exactness for the onion model (the only overlay
    model embedding TCP): identical leaves except the iteration-structure
    diagnostics (iters_done / lanes_live) every engine-equivalence suite
    excludes;
  * ensemble slice r of each overlay model is leaf-identical to a
    standalone run seeded seed + r * stride;
  * an injected chaos capacity fault on the onion scenario takes the
    standard rollback-and-regrow path and the recovered run is leaf-exact
    vs starting at the regrown capacity;
  * model-level behavior: circuits telescope to hops x clients relay
    rows, cells actually round-robin through relays, CDN misses fill
    caches downward, gossip churn toggles membership.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from topo import two_node_graph

from shadow_tpu.engine import EngineConfig, init_state
from shadow_tpu.engine.ensemble import (
    init_ensemble_state,
    replica_seeds,
    replica_slice,
    run_ensemble_until,
)
from shadow_tpu.engine.round import bootstrap, run_until
from shadow_tpu.graph import NetworkGraph, compute_routing
from shadow_tpu.models.overlay import CdnModel, GossipModel, OnionModel
from shadow_tpu.simtime import NS_PER_MS

pytestmark = pytest.mark.workload

# the engine-iteration diagnostics every engine-equivalence suite skips
# (engine/state.py: they count iteration structure, not simulation state)
_ENGINE_DIAG = ("iters_done", "lanes_live")


def _assert_leaves_exact(a, b, what="", skip=()):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for (path, la), lb in zip(fa, fb):
        key = jax.tree_util.keystr(path)
        if any(s in key for s in skip):
            continue
        assert jnp.array_equal(la, lb), f"mismatch{what} at {key}"


def _tri_node_graph(loss=0.0):
    lossy = f" packet_loss {loss}" if loss else ""
    return NetworkGraph.from_gml(
        "\n".join(
            [
                "graph [",
                "  directed 0",
                "  node [ id 0 ]",
                "  node [ id 1 ]",
                "  node [ id 2 ]",
                '  edge [ source 0 target 0 latency "1 ms" ]',
                '  edge [ source 1 target 1 latency "1 ms" ]',
                '  edge [ source 2 target 2 latency "1 ms" ]',
                f'  edge [ source 0 target 1 latency "3 ms"{lossy} ]',
                f'  edge [ source 1 target 2 latency "2 ms"{lossy} ]',
                f'  edge [ source 0 target 2 latency "5 ms"{lossy} ]',
                "]",
            ]
        )
    )


def _world(model, seed=9, queue_capacity=192, outbox_capacity=64, nodes=3,
           loss=0.0):
    graph = (
        _tri_node_graph(loss) if nodes == 3 else two_node_graph(latency_ms=3)
    )
    h = model.num_hosts
    tables = compute_routing(graph).with_hosts([i % nodes for i in range(h)])
    cfg = EngineConfig(
        num_hosts=h,
        queue_capacity=queue_capacity,
        outbox_capacity=outbox_capacity,
        runahead_ns=graph.min_latency_ns(),
        seed=seed,
        tracker=True,
    )
    return cfg, tables


def _onion(h=12, clients=5, **kw):
    return OnionModel(
        num_hosts=h, num_clients=clients, num_relays=h - clients, **kw
    )


def test_onion_pump_matches_plain():
    # lossy links: the loss-draw lane mapping and the TCP recovery paths
    # must agree between engines, not just the loss-free fast path (at
    # seed 9 the run takes real drops AND retransmits, asserted below)
    model = _onion()
    cfg, tables = _world(model, loss=0.02)
    end = 400 * NS_PER_MS

    def run(engine, k):
        c = dataclasses.replace(cfg, engine=engine, pump_k=k)
        st = bootstrap(init_state(c, model.init()), model, c)
        return run_until(st, end, model, tables, c, rounds_per_chunk=8)

    plain = run("plain", 0)
    pump = run("pump", 3)
    _assert_leaves_exact(plain, pump, " (plain vs pump)", skip=_ENGINE_DIAG)
    assert int(plain.model.streams_done.sum()) > 0  # full streams completed
    assert int(plain.packets_dropped.sum()) > 0  # loss actually exercised
    assert int(plain.model.tcp.retransmits.sum()) > 0  # ...and recovered


@pytest.mark.parametrize(
    "name",
    ["onion", "cdn", "gossip"],
)
def test_overlay_ensemble_slices_exact(name):
    model = {
        "onion": _onion(),
        "cdn": CdnModel(num_hosts=12, num_mids=1, num_leaves=2, objects=32),
        "gossip": GossipModel(num_hosts=12, view_size=4, fanout=2,
                              churn_ppm=100_000),
    }[name]
    cfg, tables = _world(model, seed=3)
    end = 200 * NS_PER_MS
    stride = 3
    ens = run_ensemble_until(
        init_ensemble_state(cfg, model, 2, stride), end, model, tables, cfg,
        rounds_per_chunk=8,
    )
    assert int(ens.events_handled.sum()) > 0
    for r, seed in enumerate(replica_seeds(cfg, 2, stride)):
        rcfg = dataclasses.replace(cfg, seed=seed)
        st = bootstrap(init_state(rcfg, model.init()), model, rcfg)
        single = run_until(st, end, model, tables, rcfg, rounds_per_chunk=8)
        _assert_leaves_exact(
            replica_slice(ens, r), single, f" ({name} replica {r})"
        )


def test_onion_chaos_capacity_recovers_leaf_exact():
    """The acceptance pin: an injected capacity fault on the onion
    scenario rolls back to the retained snapshot, regrows the saturated
    buffer, replays, and finishes leaf-exact vs a fault-free run that
    STARTED at the regrown capacity — the same bar as phold's
    test_injected_capacity_recovers_leaf_exact."""
    from shadow_tpu.runtime import chaos
    from shadow_tpu.runtime.chaos import FaultPlan
    from shadow_tpu.runtime.recovery import RecoveryPolicy, run_until_recovering

    model = _onion(h=10, clients=4)
    cfg, tables = _world(model, queue_capacity=96, outbox_capacity=48)
    end = 200 * NS_PER_MS
    st0 = bootstrap(init_state(cfg, model.init()), model, cfg)
    plan = FaultPlan(faults=[{"kind": "capacity", "at": 1}])
    with chaos.installed(plan):
        final, recoveries = run_until_recovering(
            st0, end, model, tables, cfg, rounds_per_chunk=4,
            policy=RecoveryPolicy(max_recoveries=2, snapshot_interval_chunks=2),
        )
    assert [r["kind"] for r in recoveries] == ["capacity"]
    assert recoveries[0]["injected"] is True
    grown = final.queue.capacity
    assert grown == 2 * cfg.queue_capacity  # x2 growth ladder

    cfg2 = dataclasses.replace(cfg, queue_capacity=grown)
    st2 = bootstrap(init_state(cfg2, model.init()), model, cfg2)
    reference = run_until(st2, end, model, tables, cfg2, rounds_per_chunk=4)
    _assert_leaves_exact(reference, final, " (recovered vs big-capacity)")
    assert int(final.model.streams_done.sum()) > 0


def test_onion_circuits_streams_and_scheduling():
    """Behavior pins: every client telescopes a hops-length circuit
    (circuits_built == hops * clients), streams complete end to end with
    the exact response byte count, cells flow through the scheduler, the
    exit converts whole requests, and the EWMA table shows multiplexed
    relays actually alternating circuits."""
    model = _onion(h=12, clients=5)
    cfg, tables = _world(model)
    end = 500 * NS_PER_MS
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    st = run_until(st, end, model, tables, cfg, rounds_per_chunk=8)
    m = st.model

    assert int(m.circuits_built.sum()) == model.hops * model.num_clients
    assert int(m.circuits_rejected.sum()) == 0
    done = int(m.streams_done.sum())
    # MORE than one stream per client: circuits are reused across
    # streams, so clients must keep cycling (a focus-slot regression
    # that drops the next-stream write stalls every client at 1)
    assert done > model.num_clients
    # each completed stream delivered exactly resp_span bytes to a client
    assert int(m.bytes_down.sum()) >= done * model.resp_span
    assert int(m.requests_served.sum()) >= done
    assert int(m.cells_relayed.sum()) >= done * model.resp_cells
    # some relay carries >1 circuit (5 clients x 3 hops over 7 relays) and
    # its scheduler has touched more than one of them
    live = np.asarray(m.circ_id) >= 0
    multiplexed = live.sum(axis=1) > 1
    assert multiplexed.any()
    served = np.asarray(m.ewma) > 0
    assert (served & live).sum(axis=1)[multiplexed].max() > 1
    # determinism: run-twice identical
    st2 = bootstrap(init_state(cfg, model.init()), model, cfg)
    st2 = run_until(st2, end, model, tables, cfg, rounds_per_chunk=8)
    _assert_leaves_exact(st, st2, " (run twice)")


def test_cdn_hierarchy_fills_downward():
    model = CdnModel(num_hosts=16, num_mids=1, num_leaves=3, objects=24,
                     leaf_slots=4, mid_slots=12, pause_ns=10 * NS_PER_MS)
    cfg, tables = _world(model)
    end = 400 * NS_PER_MS
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    st = run_until(st, end, model, tables, cfg, rounds_per_chunk=8)
    m = st.model
    assert int(m.reqs.sum()) > 0
    assert int(m.resp_recv.sum()) > 0
    assert int(m.misses.sum()) > 0  # cold caches missed upward
    assert int(m.hits.sum()) > 0  # ...and later requests hit
    assert int(m.fills.sum()) > 0  # responses filled caches on the way down
    # fills landed on both tiers (fan-in actually exercised the hierarchy)
    fills = np.asarray(m.fills)
    assert fills[1 : 1 + model.num_mids].sum() > 0
    assert fills[model._leaf0 : model._client0].sum() > 0
    assert int(m.bytes_down.sum()) == int(m.resp_recv.sum()) * model.obj_bytes


def test_gossip_churn_and_view_mixing():
    model = GossipModel(num_hosts=16, view_size=4, fanout=3,
                        interval_ns=10 * NS_PER_MS, churn_ppm=150_000)
    cfg, tables = _world(model)
    end = 400 * NS_PER_MS
    st = bootstrap(init_state(cfg, model.init()), model, cfg)
    st = run_until(st, end, model, tables, cfg, rounds_per_chunk=8)
    m = st.model
    assert int(m.ticks.sum()) > 0
    assert int(m.msgs_recv.sum()) > 0
    assert int(m.merges.sum()) > 0  # views actually mixed beyond the ring
    assert int(m.churn_events.sum()) > 0  # members joined/left
    assert int(m.drops_offline.sum()) > 0  # someone gossiped at a dead peer
    # views never contain self or out-of-range ids
    view = np.asarray(m.view)
    host = np.arange(model.num_hosts)[:, None]
    assert (view != host).all()
    assert ((view >= 0) & (view < model.num_hosts)).all()


def test_onion_builder_validation():
    with pytest.raises(ValueError, match="hops must be"):
        _onion(hops=5)
    with pytest.raises(ValueError, match="at least 3 relays"):
        OnionModel(num_hosts=4, num_clients=2, num_relays=2, hops=3)
    with pytest.raises(ValueError, match="clients \\+ relays"):
        OnionModel(num_hosts=4, num_clients=3, num_relays=3)
