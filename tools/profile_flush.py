"""Decompose flush_outbox's ~140 ms/round device cost (the dominant
round cost per tools/profile_while.py's F≈140ms fit): full flush vs the
argsort/rank stage vs the five 2D scatters vs scatters with
sorted+unique hints. Each variant runs as a length-N scan with the
outbox restored every iteration (so every iteration pays the full-outbox
cost), one dispatch per timing.

  python tools/profile_flush.py [hosts] [N]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 16

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import flush_outbox, run_round
    from shadow_tpu.simtime import TIME_MAX

    cfg, model, tables, st0 = _build(hosts)
    we = jnp.asarray(40_000_000, jnp.int64)

    print("warming one round (fills the outbox)...", flush=True)

    # run iterations but NOT the flush, so the outbox carries a real load
    from shadow_tpu.engine.round import handle_one_iteration

    def fill(s):
        def body(s, _):
            return handle_one_iteration(s, we, model, tables, cfg), None
        s, _ = jax.lax.scan(body, s, None, length=24)
        return s

    st = jax.jit(fill)(st0)
    jax.block_until_ready(st.events_handled)
    filled = int(np.asarray(st.outbox.fill).sum())
    print(f"outbox holds {filled} packets", flush=True)

    results = {"backend": jax.default_backend(), "hosts": hosts,
               "outbox_packets": filled, "n": n}

    def scanned(body_fn):
        def f(s):
            def body(s, _):
                s2 = body_fn(s)
                return s2.replace(outbox=s.outbox), None  # restore load
            s, _ = jax.lax.scan(body, s, None, length=n)
            return s
        return f

    # A: the real flush
    fa = jax.jit(scanned(lambda s: flush_outbox(s, None, cfg)))

    # B: sort/rank stage only (result folded into head_time to keep it live)
    def sort_only(s):
        ob = s.outbox
        h_local, o_cap = ob.valid.shape
        m = h_local * o_cap
        valid = ob.valid.reshape(m)
        dst = ob.dst.reshape(m)
        key = jnp.where(valid, dst, h_local).astype(jnp.int32)
        order = jnp.argsort(key, stable=True)
        key_s = key[order]
        pos = jnp.arange(m)
        seg = jnp.concatenate([jnp.ones((1,), bool), key_s[1:] != key_s[:-1]])
        start = jax.lax.cummax(jnp.where(seg, pos, -1))
        rank = pos - start
        probe = jnp.sum(rank) + jnp.sum(order)
        return s.replace(now=s.now + (probe % 1).astype(jnp.int64))

    fb = jax.jit(scanned(sort_only))

    # C: the five 2D scatters with trivial precomputed indices (no sort)
    def scatter_only(s):
        ob = s.outbox
        q = s.queue
        h_local, o_cap = ob.valid.shape
        m = h_local * o_cap
        sdst = (jnp.arange(m, dtype=jnp.int32) * 37) % h_local
        sslot = (jnp.arange(m, dtype=jnp.int32) * 11) % q.capacity
        q2 = q.replace(
            time=q.time.at[sdst, sslot].set(ob.time.reshape(m), mode="drop"),
            tie=q.tie.at[sdst, sslot].set(ob.tie.reshape(m), mode="drop"),
            kind=q.kind.at[sdst, sslot].set(
                jnp.zeros((m,), jnp.int32), mode="drop"),
            data=q.data.at[sdst, sslot].set(
                ob.data.reshape(m, -1), mode="drop"),
            aux=q.aux.at[sdst, sslot].set(ob.aux.reshape(m), mode="drop"),
        )
        return s.replace(queue=q2)

    fc = jax.jit(scanned(scatter_only))

    # D: same scatters with sorted + unique hints (iota indices: unique
    # when m <= h*qcap and strides coprime — use plain iota to be exact)
    def scatter_hinted(s):
        ob = s.outbox
        q = s.queue
        h_local, o_cap = ob.valid.shape
        m = h_local * o_cap
        sdst = jnp.arange(m, dtype=jnp.int32) // o_cap
        sslot = jnp.arange(m, dtype=jnp.int32) % o_cap
        kw = dict(mode="drop", indices_are_sorted=True, unique_indices=True)
        q2 = q.replace(
            time=q.time.at[sdst, sslot].set(ob.time.reshape(m), **kw),
            tie=q.tie.at[sdst, sslot].set(ob.tie.reshape(m), **kw),
            kind=q.kind.at[sdst, sslot].set(jnp.zeros((m,), jnp.int32), **kw),
            data=q.data.at[sdst, sslot].set(ob.data.reshape(m, -1), **kw),
            aux=q.aux.at[sdst, sslot].set(ob.aux.reshape(m), **kw),
        )
        return s.replace(queue=q2)

    fd = jax.jit(scanned(scatter_hinted))

    for name, f in (("flush_full", fa), ("sort_rank", fb),
                    ("scatters_plain", fc), ("scatters_hinted", fd)):
        print(f"compiling {name}...", flush=True)
        out = f(st)
        jax.block_until_ready(out.events_handled)
        t0 = time.perf_counter()
        out = f(st)
        jax.block_until_ready(out.events_handled)
        dt = (time.perf_counter() - t0) / n * 1e3
        results[f"{name}_ms"] = round(dt, 3)
        print(name, round(dt, 3), "ms", flush=True)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
