#!/usr/bin/env python3
"""Parse a shadow-tpu run's log into structured JSON (the analogue of the
reference's src/tools/parse-shadow.py, whose heartbeat format tornettools
consumes). Reads manager heartbeats and per-host tracker lines.

Usage: parse_shadow.py <logfile> [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import re
import sys

HEARTBEAT = re.compile(
    r"(?P<real>[0-9:.]+) \[info\] \[(?P<sim>[^\]]+)\] \[manager\] "
    r"heartbeat: (?P<a>\d+) (?:events|syscalls), (?P<packets>\d+) packets"
)
TRACKER = re.compile(
    r"\[(?P<sim>[^\]]+)\] \[(?P<host>[^\]]+)\] tracker: "
    r"bytes_sent=(?P<tx>\d+) bytes_recv=(?P<rx>\d+) "
    r"packets_sent=(?P<ptx>\d+) packets_dropped=(?P<drop>\d+)"
)
FINISHED = re.compile(r"finished: .* in (?P<wall>[0-9.]+)s wall")


def parse(lines):
    out = {"heartbeats": [], "hosts": {}, "wall_seconds": None}
    for line in lines:
        m = HEARTBEAT.search(line)
        if m:
            out["heartbeats"].append(
                {
                    "sim_time": m.group("sim"),
                    "work": int(m.group("a")),
                    "packets": int(m.group("packets")),
                }
            )
            continue
        m = TRACKER.search(line)
        if m:
            out["hosts"].setdefault(m.group("host"), []).append(
                {
                    "sim_time": m.group("sim"),
                    "bytes_sent": int(m.group("tx")),
                    "bytes_recv": int(m.group("rx")),
                    "packets_sent": int(m.group("ptx")),
                    "packets_dropped": int(m.group("drop")),
                }
            )
            continue
        m = FINISHED.search(line)
        if m:
            out["wall_seconds"] = float(m.group("wall"))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("logfile")
    ap.add_argument("-o", "--output", default=None)
    args = ap.parse_args(argv)
    with open(args.logfile) as f:
        data = parse(f)
    text = json.dumps(data, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
