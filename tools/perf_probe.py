"""Quick engine-throughput probe at bench scale (bounded horizon so the
tunneled TPU worker survives). Usage:

  python tools/perf_probe.py [hosts] [sim_ms] [active_lanes] [rpc]

Prints one JSON line with wall time, events, and events/s for the tgen
bench workload (same builder as bench.py)."""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    sim_ms = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    lanes = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    rpc = int(sys.argv[4]) if len(sys.argv) > 4 else 16

    import dataclasses
    import os

    import jax
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import run_until

    cfg, model, tables, st0 = _build(hosts)
    if lanes:
        cfg = dataclasses.replace(cfg, active_lanes=lanes)
    # experiment knobs (bottleneck isolation)
    overrides = {}
    if os.environ.get("SHADOW_PROBE_QCAP"):
        overrides["queue_capacity"] = int(os.environ["SHADOW_PROBE_QCAP"])
    if os.environ.get("SHADOW_PROBE_NETSTACK") == "0":
        overrides["use_netstack"] = False
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
        from shadow_tpu.engine.round import bootstrap
        from shadow_tpu.engine.state import init_state
        from shadow_tpu.netstack import bw_bits_per_sec_to_refill

        bw = bw_bits_per_sec_to_refill(100_000_000) if cfg.use_netstack else None
        st0 = bootstrap(
            init_state(cfg, model.init(), tx_bytes_per_interval=bw, rx_bytes_per_interval=bw),
            model,
            cfg,
        )
    end = sim_ms * 1_000_000

    t0 = time.perf_counter()
    run_until(st0, 2_000_000, model, tables, cfg, rounds_per_chunk=rpc)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    st = run_until(st0, end, model, tables, cfg, rounds_per_chunk=rpc, max_chunks=1_000_000)
    jax.block_until_ready(st.events_handled)
    wall = time.perf_counter() - t0
    ev = int(np.asarray(st.events_handled).sum())
    iters = int(np.asarray(st.iters_done).sum())
    print(
        json.dumps(
            {
                "backend": jax.default_backend(),
                "hosts": hosts,
                "sim_ms": sim_ms,
                "active_lanes": lanes,
                "rpc": rpc,
                "compile_s": round(compile_s, 1),
                "wall_s": round(wall, 2),
                "events": ev,
                "events_per_s": int(ev / wall) if wall > 0 else None,
                "sim_per_wall": round(sim_ms / 1000.0 / wall, 4),
                "iters": iters,
                "events_per_iter": round(ev / iters, 2) if iters else None,
                "us_per_iter": round(wall / iters * 1e6, 1) if iters else None,
                "streams_done": int(np.asarray(st.model.streams_done).sum()),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
