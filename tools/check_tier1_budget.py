"""Tier-1 wall-budget check: fail when the quick tier exceeds its cap.

The quick tier (``pytest -m 'not slow'``) runs under a hard 870-second
wall (ROADMAP.md "Tier-1 verify"; the driver kills the run past it), so
every PR that adds quick tests must prove the tier still fits.  The
conftest SLOW_TESTS rebalance comments record the history of breaches;
this tool turns the check into a command:

    python tools/check_tier1_budget.py /tmp/_t1.log

It parses the wall-clock seconds from the pytest summary line of a
COMPLETED quick-tier run log (the ``tee`` target of the verify recipe),
compares against the cap in tools/tier1_budget.json, and exits non-zero
with a one-line verdict when the tier is over budget — or within
``warn_margin_s`` of it, because a tier that "fits" with 3s to spare on
one box is a breach on a slower day (the PR-15 rebalance found exactly
that).  On success it rewrites the budget file's ``measured_s`` so the
repo carries the latest measurement.

No dependencies beyond the standard library: the check must run in the
barest CI shell, before any environment is built.
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

BUDGET_FILE = Path(__file__).with_name("tier1_budget.json")

# the pytest-8 summary line: "== 228 passed, 1 failed, 96 deselected,
# 3 warnings in 612.34s (0:10:12) ==" (the parenthesized clock only
# appears past 60s; both forms parse)
_SUMMARY_RE = re.compile(
    r"in\s+(?P<secs>\d+(?:\.\d+)?)s(?:\s+\(\d+:\d{2}:\d{2}\))?\s*=*\s*$"
)


def parse_wall_seconds(log_text: str) -> "float | None":
    """Wall seconds from the LAST pytest summary line in the log, or
    None when the log holds no completed run (e.g. the driver's timeout
    killed it — which is itself a budget verdict, handled in main)."""
    wall = None
    for line in log_text.splitlines():
        m = _SUMMARY_RE.search(line)
        if m and ("passed" in line or "failed" in line or "error" in line):
            wall = float(m.group("secs"))
    return wall


def load_budget(path: Path = BUDGET_FILE) -> dict:
    return json.loads(path.read_text())


def verdict(wall_s: "float | None", budget: dict) -> "tuple[int, str]":
    """(exit code, one-line message) for a measured quick-tier wall."""
    cap = float(budget["wall_cap_s"])
    margin = float(budget.get("warn_margin_s", 0))
    if wall_s is None:
        return 2, (
            f"tier-1 budget: no completed pytest summary in the log — "
            f"the run was likely killed at the {cap:.0f}s cap; rebalance "
            f"tests/conftest.py SLOW_TESTS before shipping"
        )
    if wall_s > cap:
        return 1, (
            f"tier-1 budget EXCEEDED: quick tier took {wall_s:.1f}s against "
            f"the {cap:.0f}s cap; move tests into tests/conftest.py "
            f"SLOW_TESTS (keep a quick pin per plane) and re-measure"
        )
    if wall_s > cap - margin:
        return 1, (
            f"tier-1 budget at risk: {wall_s:.1f}s is within the "
            f"{margin:.0f}s safety margin of the {cap:.0f}s cap "
            f"({cap - wall_s:.1f}s headroom); rebalance now, not after "
            f"the next breach"
        )
    return 0, (
        f"tier-1 budget ok: {wall_s:.1f}s of the {cap:.0f}s cap "
        f"({cap - wall_s:.1f}s headroom)"
    )


def main(argv: "list[str]") -> int:
    budget_file = BUDGET_FILE
    if len(argv) == 3 and argv[0] == "--budget":
        budget_file = Path(argv[1])
        argv = argv[2:]
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.strip().splitlines()[0])
        print(
            "usage: python tools/check_tier1_budget.py "
            "[--budget tier1_budget.json] <quick-tier pytest log>"
        )
        return 2
    log_path = Path(argv[0])
    if not log_path.exists():
        print(f"tier-1 budget: log file {log_path} not found")
        return 2
    budget = load_budget(budget_file)
    wall = parse_wall_seconds(log_path.read_text(errors="replace"))
    code, msg = verdict(wall, budget)
    print(msg)
    if code == 0:
        budget["measured_s"] = wall
        budget_file.write_text(json.dumps(budget, indent=2) + "\n")
    return code


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
