#!/usr/bin/env python3
"""Bench trajectory: parse every BENCH_r*.json into a per-round table
and flag regressions against the best prior round.

The BENCH_r* files are the repo's published performance record (one per
growth round: {"n", "parsed": {"metric", "value", "detail": ...}}), but
nothing ever read them BACK — a regression (or a round publishing null,
like r05) was only visible to a human diffing JSON. This tool is the
read side:

  * `load_rounds` — one record per round: the metric value, the rung
    ladder each attempt walked (hosts / rounds_per_chunk / wall /
    failure kind), and the measuring config;
  * `trajectory_table` — the human-readable per-round table;
  * `regression_check` — the latest value (or an in-flight value passed
    by bench.py) vs the best prior round, with a structured verdict.

bench.py runs this at the end of every bench and prints the delta line
into the bench log, so every BENCH_r*.json from now on carries its own
trajectory context.

Usage: python tools/bench_history.py [ROOT] [--current VALUE] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os

# latest < best_prior * (1 - TOLERANCE) flags a regression; the slack
# absorbs run-to-run noise on contended hosts without hiding a real slide
TOLERANCE = 0.05


def _attempt_row(att: dict) -> dict:
    cfg = att.get("config", {})
    row = {
        "ok": bool(att.get("ok")),
        "hosts": cfg.get("hosts"),
        "rounds_per_chunk": cfg.get("rounds_per_chunk"),
    }
    if att.get("wall_s") is not None:
        row["wall_s"] = att["wall_s"]
    failure = att.get("failure")
    if isinstance(failure, dict):
        row["failure"] = failure.get("kind", "?")
    elif not row["ok"]:
        err = str(att.get("error", ""))
        row["failure"] = (
            "timeout" if "timeout" in err.lower() else (err[:40] or "?")
        )
    return row


def _service_row(detail: dict) -> "dict | None":
    """The service-plane SLO pair a round published: detail.service
    (the daemon trial, ISSUE 11) with a fallback to the older
    detail.sweep block, so the trajectory reaches back before the
    daemon landed. None when the round measured neither."""
    svc = detail.get("service") or {}
    row = {
        "jobs_per_hour": svc.get("jobs_per_hour"),
        "cache_hit_rate": svc.get("cache_hit_rate"),
    }
    # fleet-wide admission latency (ISSUE 20): lower-is-better, only
    # present once the HTTP+fleet rung started publishing it
    if svc.get("admit_latency_p99_s") is not None:
        row["admit_latency_p99_s"] = svc["admit_latency_p99_s"]
    if row["jobs_per_hour"] is None:
        sweep = detail.get("sweep") or {}
        row["jobs_per_hour"] = sweep.get("jobs_per_hour")
        row["cache_hit_rate"] = (sweep.get("compile_cache") or {}).get(
            "hit_rate"
        )
    if row["jobs_per_hour"] is None and row["cache_hit_rate"] is None:
        return None
    return row


def _overlay_row(detail: dict) -> "dict | None":
    """Per-model overlay throughput a round published: detail.overlay
    (the overlay workload trial, ISSUE 12) as {"model@Nh":
    events_per_sec}. Keyed by model AND world size: bench.py measures
    each model at two sizes and a salvaged partial round may only carry
    the small one — comparing across sizes would flag phantom
    regressions, so each (model, hosts) pair tracks its own history.
    None when the round measured no overlay model."""
    ov = detail.get("overlay") or {}
    row = {}
    for r in ov.get("rows", []):
        model, hosts = r.get("model"), r.get("hosts")
        eps = r.get("events_per_sec")
        if model and eps is not None:
            row[f"{model}@{hosts}h"] = eps
    return row or None


def _mesh_row(detail: dict) -> "dict | None":
    """Per-grid 2-D mesh throughput a round published: detail.mesh
    (the mesh trial, ISSUE 14) as {"<kind><grid>@Nh": sim_s_per_wall_s}
    — e.g. "mesh2x4@128h" with its "ensemble4x1@128h" / "sharded1x8@128h"
    baselines. Keyed by plane, grid AND world size so salvaged partial
    rounds never compare across shapes. None when the round measured no
    mesh row."""
    ms = detail.get("mesh") or {}
    hosts = ms.get("hosts", "?")
    row = {}
    for r in ms.get("rows", []):
        kind, grid = r.get("kind"), r.get("grid")
        v = r.get("sim_s_per_wall_s")
        if kind and grid and v is not None:
            row[f"{kind}{grid}@{hosts}h"] = v
    return row or None


def _elastic_row(detail: dict) -> "dict | None":
    """The elastic-mesh reshape row a round published: detail.elastic
    (the elastic trial, ISSUE 15) as {"reshape_replay_wall_s@<grid>@Nh":
    seconds} — the wall cost of one device-loss rung (rollback +
    re-plan + recompile + replay to the point of loss). LOWER is
    better, so elastic_check inverts the comparison direction. Keyed by
    grid and world size so rows never compare across shapes."""
    el = detail.get("elastic") or {}
    hosts = el.get("hosts", "?")
    grid = el.get("grid", "?")
    v = el.get("reshape_replay_wall_s")
    if v is None:
        return None
    return {f"reshape_replay_wall_s@{grid}@{hosts}h": v}


def _exchange_row(detail: dict) -> "dict | None":
    """The dense-vs-segment exchange rows a round published:
    detail.exchange (the exchange trial, event-exchange v2 round) as
    {"flush_ms.<mode>@Nh" / "bytes_per_host.<mode>@Nh": value}. Both
    are wall/wire costs, so exchange_check inverts the comparison
    direction (lower is better). Keyed by mode AND world size so
    salvaged partial rounds never compare across shapes. None when the
    round measured no exchange row."""
    ex = detail.get("exchange") or {}
    row = {
        k: v
        for k, v in (ex.get("summary") or {}).items()
        if k.startswith(("flush_ms.", "bytes_per_host."))
        and v is not None
    }
    return row or None


def _memory_row(detail: dict) -> "dict | None":
    """The device-memory rows the main trial published: detail.main.memory
    (the memory observatory round) as {"bytes_per_host@Nh": bytes} plus
    the compiled peak when the backend reported one. Memory is a cost, so
    memory_check inverts the comparison direction (lower is better).
    Keyed by world size so salvaged partial rounds never compare across
    shapes. None when the round priced nothing."""
    mem = (detail.get("main") or {}).get("memory") or {}
    hosts = (detail.get("config") or {}).get("hosts", "?")
    row = {}
    if mem.get("bytes_per_host") is not None:
        row[f"bytes_per_host@{hosts}h"] = mem["bytes_per_host"]
    if mem.get("peak_hbm_bytes") is not None:
        row[f"peak_hbm_bytes@{hosts}h"] = mem["peak_hbm_bytes"]
    return row or None


def _metric_verdicts(rounds_key: str, keys, history, current,
                     latest_round, lower_is_better: bool = False) -> dict:
    """The shared best-prior/TOLERANCE verdict core behind
    service_check, overlay_check, and elastic_check (and
    regression_check's policy): for each key, compare `current[key]`
    against the best prior round's value under `rounds_key`, flagging a
    slide past TOLERANCE — and flagging a NULL latest when a prior
    round did measure it (the r05 policy: a metric that stops being
    published must announce itself). `lower_is_better` inverts the
    direction for wall/cost metrics: best prior is the minimum and a
    slide is the value GROWING past tolerance."""
    out = {"latest_round": latest_round, "regression": False}
    verdicts = {}
    pick = min if lower_is_better else max
    for key in keys:
        cur = (current or {}).get(key)
        prior = [r for r in history if r[rounds_key].get(key) is not None]
        best = (
            pick(prior, key=lambda r: r[rounds_key][key]) if prior else None
        )
        v = {
            "latest": cur,
            "best_prior": best[rounds_key][key] if best else None,
            "best_prior_round": best["round"] if best else None,
        }
        if best is None:
            v["regression"] = False
            v["note"] = "no prior round measured this"
        elif cur is None:
            v["regression"] = True
            v["note"] = (
                f"latest is null vs best {v['best_prior']} "
                f"(r{v['best_prior_round']})"
            )
        else:
            delta = (cur - v["best_prior"]) / max(v["best_prior"], 1e-9)
            v["delta_pct"] = round(delta * 100, 1)
            v["regression"] = (
                delta > TOLERANCE if lower_is_better else delta < -TOLERANCE
            )
            v["note"] = (
                f"{'REGRESSION' if v['regression'] else 'ok'}: "
                f"{cur:.4g} vs best {v['best_prior']:.4g} "
                f"(r{v['best_prior_round']}, {v['delta_pct']:+.1f}%)"
            )
        verdicts[key] = v
        out["regression"] = out["regression"] or v["regression"]
    return out, verdicts


def _pop_latest(rounds_key: str, rounds, current):
    """History rows carrying `rounds_key`, with the newest one promoted
    to `current` when the caller passed none (the recorded-rounds mode
    of the check functions)."""
    history = [r for r in rounds if r.get(rounds_key)]
    latest_round = None
    if current is None and history:
        last = history[-1]
        current, latest_round = last[rounds_key], last["round"]
        history = history[:-1]
    return history, current, latest_round


def overlay_check(rounds: "list[dict]",
                  current: "dict | None" = None) -> dict:
    """The detail.overlay trajectory verdicts — each overlay model's
    events_per_sec (per world size, "model@Nh") gets the SAME
    best-prior/TOLERANCE flagging as the headline metric. `current` is
    an in-flight {"model@Nh": events_per_sec} from bench.py; None
    compares the newest recorded round against the rest."""
    history, current, latest_round = _pop_latest("overlay", rounds, current)
    keys = sorted(
        set(current or {}) | {m for r in history for m in r["overlay"]}
    )
    out, verdicts = _metric_verdicts(
        "overlay", keys, history, current, latest_round
    )
    out["models"] = verdicts
    return out


def mesh_check(rounds: "list[dict]",
               current: "dict | None" = None) -> dict:
    """The detail.mesh trajectory verdicts — each (plane, grid, size)
    row's sim_s_per_wall_s gets the SAME best-prior/TOLERANCE flagging
    as the headline metric. `current` is an in-flight
    {"<kind><grid>@Nh": rate} from bench.py; None compares the newest
    recorded round against the rest."""
    history, current, latest_round = _pop_latest("mesh", rounds, current)
    keys = sorted(
        set(current or {}) | {m for r in history for m in r["mesh"]}
    )
    out, verdicts = _metric_verdicts(
        "mesh", keys, history, current, latest_round
    )
    out["grids"] = verdicts
    return out


def elastic_check(rounds: "list[dict]",
                  current: "dict | None" = None) -> dict:
    """The detail.elastic trajectory verdicts — the reshape-replay WALL
    per (grid, size) row, the SAME best-prior/TOLERANCE core as every
    other detail metric but with the direction inverted (a wall metric:
    lower is better). `current` is an in-flight
    {"reshape_replay_wall_s@<grid>@Nh": seconds} from bench.py; None
    compares the newest recorded round against the rest."""
    history, current, latest_round = _pop_latest("elastic", rounds, current)
    keys = sorted(
        set(current or {}) | {m for r in history for m in r["elastic"]}
    )
    out, verdicts = _metric_verdicts(
        "elastic", keys, history, current, latest_round,
        lower_is_better=True,
    )
    out["rows"] = verdicts
    return out


def exchange_check(rounds: "list[dict]",
                   current: "dict | None" = None) -> dict:
    """The detail.exchange trajectory verdicts — flush wall and
    collective bytes/host per exchange mode, the SAME best-prior/
    TOLERANCE core as every other detail metric with the direction
    inverted (wall and wire costs: lower is better). `current` is an
    in-flight {"flush_ms.<mode>@Nh": ms, ...} from bench.py; None
    compares the newest recorded round against the rest."""
    history, current, latest_round = _pop_latest("exchange", rounds, current)
    keys = sorted(
        set(current or {}) | {m for r in history for m in r["exchange"]}
    )
    out, verdicts = _metric_verdicts(
        "exchange", keys, history, current, latest_round,
        lower_is_better=True,
    )
    out["rows"] = verdicts
    return out


def memory_check(rounds: "list[dict]",
                 current: "dict | None" = None) -> dict:
    """The detail.main.memory trajectory verdicts — priced bytes/host
    (and compiled peak HBM) per world size, the SAME best-prior/
    TOLERANCE core as every other detail metric with the direction
    inverted (memory is a cost: a perf round that quietly doubles the
    footprint must announce itself). `current` is an in-flight
    {"bytes_per_host@Nh": bytes, ...} from bench.py; None compares the
    newest recorded round against the rest."""
    history, current, latest_round = _pop_latest("memory", rounds, current)
    keys = sorted(
        set(current or {}) | {m for r in history for m in r["memory"]}
    )
    out, verdicts = _metric_verdicts(
        "memory", keys, history, current, latest_round,
        lower_is_better=True,
    )
    out["rows"] = verdicts
    return out


def service_check(rounds: "list[dict]",
                  current: "dict | None" = None) -> dict:
    """The detail.service trajectory verdicts — jobs_per_hour and
    cache_hit_rate get the SAME best-prior/TOLERANCE flagging the
    headline metric gets (regression_check), and admit_latency_p99_s
    (the ISSUE-20 admission-latency satellite) the inverted
    lower-is-better direction. `current` is an in-flight
    {jobs_per_hour, cache_hit_rate, admit_latency_p99_s} from bench.py;
    None compares the newest recorded round against the rest."""
    history, current, latest_round = _pop_latest("service", rounds, current)
    out, verdicts = _metric_verdicts(
        "service", ("jobs_per_hour", "cache_hit_rate"), history, current,
        latest_round,
    )
    # latency is a cost: only flag it once some round has measured it
    # (pre-ISSUE-20 history must not turn every round into a null-slide)
    if (current or {}).get("admit_latency_p99_s") is not None or any(
        r["service"].get("admit_latency_p99_s") is not None
        for r in history
    ):
        out_lat, v_lat = _metric_verdicts(
            "service", ("admit_latency_p99_s",), history, current,
            latest_round, lower_is_better=True,
        )
        out["regression"] = out["regression"] or out_lat["regression"]
        verdicts.update(v_lat)
    out["metrics"] = verdicts
    return out


def load_rounds(root: str = ".") -> "list[dict]":
    """One record per BENCH_r*.json, sorted by round number. Tolerant of
    missing/partial fields — a malformed round becomes a null-value row,
    never an exception."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") or {}
        detail = parsed.get("detail") or {}
        main = detail.get("main") or {}
        rec = {
            "round": doc.get("n"),
            "file": os.path.basename(path),
            "metric": parsed.get("metric"),
            "value": parsed.get("value"),
            "vs_baseline": parsed.get("vs_baseline"),
            "hosts": (detail.get("config") or {}).get("hosts"),
            "rounds_per_chunk": (detail.get("config") or {}).get(
                "rounds_per_chunk"
            ),
            "wall_s": main.get("wall_s"),
            "partial": bool(main.get("partial")),
            "service": _service_row(detail),
            "overlay": _overlay_row(detail),
            "mesh": _mesh_row(detail),
            "elastic": _elastic_row(detail),
            "exchange": _exchange_row(detail),
            "memory": _memory_row(detail),
            "attempts": [
                _attempt_row(a) for a in detail.get("attempts", [])
            ],
        }
        rec["failure_kinds"] = sorted(
            {a["failure"] for a in rec["attempts"] if a.get("failure")}
        )
        rounds.append(rec)
    rounds.sort(key=lambda r: (r["round"] is None, r["round"]))
    return rounds


def trajectory_table(rounds: "list[dict]") -> str:
    """The per-round trajectory: metric value, measuring rung, per-rung
    walls, and the failure kinds each round survived (or died of)."""
    lines = [
        f"{'round':>5} {'value':>10} {'hosts':>8} {'rpc':>5} {'wall_s':>8} "
        f"{'rungs':>5} {'jobs/h':>8} {'hit':>5}  failures"
    ]
    for r in rounds:
        val = "null" if r["value"] is None else f"{r['value']:.4f}"
        svc = r.get("service") or {}
        jph = svc.get("jobs_per_hour")
        hit = svc.get("cache_hit_rate")
        lines.append(
            f"{r['round'] if r['round'] is not None else '?':>5} "
            f"{val:>10}{'*' if r['partial'] else ' '}"
            f"{r['hosts'] if r['hosts'] is not None else '-':>7} "
            f"{r['rounds_per_chunk'] or '-':>5} "
            f"{r['wall_s'] if r['wall_s'] is not None else '-':>8} "
            f"{len(r['attempts']):>5} "
            f"{jph if jph is not None else '-':>8} "
            f"{f'{hit:.2f}' if hit is not None else '-':>5}  "
            f"{','.join(r['failure_kinds']) or '-'}"
        )
    return "\n".join(lines)


def regression_check(rounds: "list[dict]",
                     current: "float | None" = None) -> dict:
    """The delta verdict: `current` (an in-flight bench value) — or the
    newest recorded round when None — against the best prior round.
    `regression` is True when the latest is null or more than TOLERANCE
    below the best prior value."""
    history = list(rounds)
    latest_round = None
    if current is None and history:
        last = history[-1]
        current, latest_round = last["value"], last["round"]
        history = history[:-1]
    prior = [r for r in history if r["value"] is not None]
    best = max(prior, key=lambda r: r["value"]) if prior else None
    out = {
        "latest": current,
        "latest_round": latest_round,
        "best_prior": best["value"] if best else None,
        "best_prior_round": best["round"] if best else None,
        "rounds": len(rounds),
    }
    if best is None:
        out["regression"] = current is None
        out["note"] = "no prior non-null round"
        return out
    if current is None:
        out["regression"] = True
        out["note"] = f"latest is null vs best {best['value']} (r{best['round']})"
        return out
    delta = (current - best["value"]) / best["value"]
    out["delta_pct"] = round(delta * 100, 1)
    out["regression"] = delta < -TOLERANCE
    out["note"] = (
        f"{'REGRESSION' if out['regression'] else 'ok'}: "
        f"{current:.4f} vs best {best['value']:.4f} "
        f"(r{best['round']}, {out['delta_pct']:+.1f}%)"
    )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH_r*.json trajectory table + regression flag"
    )
    ap.add_argument("root", nargs="?", default=".",
                    help="repo root holding BENCH_r*.json (default .)")
    ap.add_argument("--current", type=float, default=None,
                    help="an in-flight bench value to compare against the "
                    "best recorded round")
    ap.add_argument("--json", action="store_true",
                    help="emit the parsed rounds + verdict as JSON")
    args = ap.parse_args(argv)
    rounds = load_rounds(args.root)
    verdict = regression_check(rounds, current=args.current)
    svc = service_check(rounds)
    ovl = overlay_check(rounds)
    msh = mesh_check(rounds)
    ela = elastic_check(rounds)
    exc = exchange_check(rounds)
    mem = memory_check(rounds)
    if args.json:
        print(json.dumps(
            {"rounds": rounds, "verdict": verdict, "service": svc,
             "overlay": ovl, "mesh": msh, "elastic": ela,
             "exchange": exc, "memory": mem}, indent=2
        ))
    else:
        print(trajectory_table(rounds))
        print(verdict.get("note", ""))
        for metric, v in svc["metrics"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"service.{metric}: {v['note']}")
        for model, v in ovl["models"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"overlay.{model}: {v['note']}")
        for grid, v in msh["grids"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"mesh.{grid}: {v['note']}")
        for row, v in ela["rows"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"elastic.{row}: {v['note']}")
        for row, v in exc["rows"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"exchange.{row}: {v['note']}")
        for row, v in mem["rows"].items():
            if v.get("latest") is not None or v.get("best_prior") is not None:
                print(f"memory.{row}: {v['note']}")
    return 1 if (
        verdict.get("regression")
        or svc.get("regression")
        or ovl.get("regression")
        or msh.get("regression")
        or ela.get("regression")
        or exc.get("regression")
        or mem.get("regression")
    ) else 0


if __name__ == "__main__":
    raise SystemExit(main())
