"""Build + run the native C baseline (tgen_pdes.c) on the bench topology.

Dumps the exact routing tables bench.py:_build constructs (so the C PDES
simulates the identical world), compiles the C once, runs it, and prints
its one-line JSON result. Used by bench.py for the honest `vs_baseline`
denominator and by tests/test_native_baseline.py for counter-identity
against the device engine and the Python oracle.

  python tools/native_baseline/run_native_baseline.py [hosts] [sim_sec]
"""

from __future__ import annotations

import os
import pathlib
import struct
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent
REPO = HERE.parent.parent

NS_PER_SEC = 1_000_000_000


def ensure_built() -> pathlib.Path:
    src = HERE / "tgen_pdes.c"
    out = HERE / "build" / "tgen_pdes"
    out.parent.mkdir(exist_ok=True)
    if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
        subprocess.run(
            ["cc", "-O2", "-o", str(out), str(src), "-lm"],
            check=True,
            capture_output=True,
            text=True,
        )
    return out


def write_tables(path, tables) -> None:
    """The one serializer of the C binary's tables format:
    int32 n_nodes, int64 lat[n*n] ns, float32 rel[n*n]."""
    import numpy as np

    lat = np.asarray(tables.lat_ns, dtype=np.int64)
    rel = np.asarray(tables.rel, dtype=np.float32)
    with open(path, "wb") as f:
        f.write(struct.pack("<i", lat.shape[0]))
        f.write(lat.tobytes())
        f.write(rel.tobytes())


def dump_tables(path: pathlib.Path, num_hosts: int, seed: int = 7):
    """Writes the bench topology's lat/rel node tables; returns the engine
    config pieces the C binary needs (runahead, bandwidth refill) — all
    read from bench._build's world, never duplicated here."""
    sys.path.insert(0, str(REPO))
    from bench import HOST_BW_BITS, _build_world

    # world only — never init_state/bootstrap (at 160k+ hosts the device
    # state is multi-GB and the C binary needs none of it)
    cfg, model, tables = _build_world(num_hosts, seed=seed)
    write_tables(path, tables)
    from shadow_tpu.netstack import bw_bits_per_sec_to_refill

    return {
        "runahead_ns": cfg.runahead_ns,
        "refill": bw_bits_per_sec_to_refill(HOST_BW_BITS),
        "resp_bytes": model.resp_bytes,
        "pause_ns": model.pause_ns,
        "seed": cfg.seed,
    }


def run(num_hosts: int, sim_sec: float, tables_path=None) -> str:
    binary = ensure_built()
    tp = pathlib.Path(tables_path or (HERE / "build" / f"tables_{num_hosts}.bin"))
    meta = dump_tables(tp, num_hosts)
    r = subprocess.run(
        [
            str(binary),
            str(tp),
            str(num_hosts),
            str(int(sim_sec * NS_PER_SEC)),
            str(meta["seed"]),
            str(meta["resp_bytes"]),
            str(meta["pause_ns"]),
            str(meta["runahead_ns"]),
            str(meta["refill"]),
            str(meta["refill"]),
        ],
        check=True,
        capture_output=True,
        text=True,
    )
    return r.stdout.strip()


if __name__ == "__main__":
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else int(
        os.environ.get("SHADOW_TPU_BENCH_HOSTS", 10240)
    )
    sim_sec = float(sys.argv[2]) if len(sys.argv) > 2 else 0.5
    print(run(hosts, sim_sec))
