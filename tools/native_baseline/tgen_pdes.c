/* Native-speed CPU PDES of the exact flagship-bench semantics (tgen
 * request/response streams over the engine's TCP + netstack), serving as
 * the honest performance baseline the round-3 verdict asked for: a
 * thread_per_core-grade native stand-in (reference:
 * src/main/core/scheduler/thread_per_core.rs:12-115) instead of the
 * JAX-on-CPU strawman.
 *
 * This is a C port of OUR OWN scalar conformance oracle
 * (shadow_tpu/cpu_ref/tcp_ref.py + tgen_ref.py + netstack_ref.py + the
 * engine window loop of engine/round.py), bit-identical by construction:
 * the same threefry draws (validated against jax in
 * tests/test_native_baseline.py), the same integer TCP/shaping
 * arithmetic, the same (time, tie) total order. Counter equality with
 * the device engine on the same configuration is asserted by tests, so
 * the published baseline provably computes the same simulation.
 *
 * Input: a binary tables file (int32 n_nodes, int64 lat[n*n] ns,
 * float rel[n*n]) written by tools/native_baseline/run_native_baseline.py
 * from the bench topology; host->node mapping is i % n_nodes as in
 * bench.py:_build.
 *
 * Usage: tgen_pdes TABLES_FILE NUM_HOSTS SIM_NS [SEED] [RESP_BYTES]
 *        [PAUSE_NS] [RUNAHEAD_NS] [TX_REFILL] [RX_REFILL]
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

/* ---------------- threefry2x32 (jax-compatible) ---------------- */

typedef struct { uint32_t k0, k1; } Key;

static void threefry2x32(uint32_t k0, uint32_t k1, uint32_t x0, uint32_t x1,
                         uint32_t *o0, uint32_t *o1) {
    static const int rot[8] = {13, 15, 26, 6, 17, 29, 16, 24};
    uint32_t ks[3] = {k1, k0 ^ k1 ^ 0x1BD11BDAu, k0};
    x0 += k0;
    x1 += k1;
    for (int grp = 0; grp < 5; grp++) {
        for (int r = 0; r < 4; r++) {
            x0 += x1;
            int d = rot[(grp % 2) * 4 + r];
            x1 = (x1 << d) | (x1 >> (32 - d));
            x1 ^= x0;
        }
        x0 += ks[grp % 3];
        x1 += ks[(grp + 1) % 3] + (uint32_t)(grp + 1);
    }
    *o0 = x0;
    *o1 = x1;
}

static Key fold_in(Key k, uint32_t data) {
    Key r;
    threefry2x32(k.k0, k.k1, 0, data, &r.k0, &r.k1);
    return r;
}

/* jax.random.uniform(key, dtype=f32): bits = x0^x1 of threefry(key,(0,0));
 * float = bitcast(bits>>9 | 0x3f800000) - 1.0 */
static float uniform_f32(Key k) {
    uint32_t b0, b1;
    threefry2x32(k.k0, k.k1, 0, 0, &b0, &b1);
    uint32_t bits = ((b0 ^ b1) >> 9) | 0x3f800000u;
    float f;
    memcpy(&f, &bits, 4);
    return f - 1.0f;
}

/* ---------------- constants mirroring the engine ---------------- */

#define NS_PER_MS 1000000LL
#define NS_PER_SEC 1000000000LL
#define TIME_MAX 0x7fffffffffffffffLL

#define KIND_PACKET 0
#define KIND_TCP_TIMER 1 /* KIND_MODEL_BASE + 0 */
#define KIND_TCP_FLUSH 2 /* KIND_MODEL_BASE + 1 */
#define KIND_STREAM_START 9 /* TCP_KIND_USER_BASE */

#define LANE_PORTS 0
#define LANE_SEQ 1
#define LANE_ACK 2
#define LANE_FLAGS_LEN 3
#define LANE_WND 4
#define LANE_SACK_S 6
#define LANE_SACK_E 7
#define PAYLOAD_LANES 8

#define FLAG_FIN 0x01
#define FLAG_SYN 0x02
#define FLAG_RST 0x04
#define FLAG_ACK 0x10

#define AUX_SIZE_MASK ((1 << 24) - 1)
#define AUX_SHAPED_BIT (1 << 24)

/* TCP states */
enum { CLOSED, LISTEN, SYNSENT, SYNRECEIVED, ESTABLISHED, FINWAIT1,
       FINWAIT2, CLOSING, TIMEWAIT, CLOSEWAIT, LASTACK };

/* TcpParams (TGEN_TCP: 4 sockets, 1 s timewait; rest defaults) */
#define NSOCK 4
#define MSS 1460
#define HDR_BYTES 40
#define RCV_WND (256 * 1024)
#define INIT_CWND_SEGS 10
#define RTO_INIT NS_PER_SEC
#define RTO_MIN (200 * NS_PER_MS)
#define RTO_MAX (60 * NS_PER_SEC)
#define GRANULARITY NS_PER_MS
#define OOO_RANGES 4
#define SEGS_PER_FLUSH 4
#define PACKET_LANES (SEGS_PER_FLUSH + 1)
#define LOCAL_LANES 4 /* tcp flush + tcp timer + model flush + next-stream */
#define USE_SACK 1

/* netstack (netstack_ref.py spec) */
#define REFILL_INTERVAL_NS 1000000LL
#define CODEL_TARGET_NS 10000000LL
#define CODEL_INTERVAL_NS 100000000LL
#define MTU_BYTES 1500

/* tgen model */
#define TGEN_PORT 80
#define START_NS NS_PER_MS
#define REQ_BYTES 64

/* ---------------- event heap, keyed (time, tie) ---------------- */

typedef struct {
    int64_t time, tie;
    int32_t kind, aux;
    int32_t data[PAYLOAD_LANES];
} Ev;

typedef struct {
    Ev *a;
    int n, cap;
} Heap;

static inline int ev_lt(const Ev *x, const Ev *y) {
    if (x->time != y->time)
        return x->time < y->time;
    return x->tie < y->tie;
}

static void heap_push(Heap *h, Ev e) {
    if (h->n == h->cap) {
        h->cap = h->cap ? h->cap * 2 : 16;
        h->a = realloc(h->a, (size_t)h->cap * sizeof(Ev));
    }
    int i = h->n++;
    while (i > 0) {
        int p = (i - 1) / 2;
        if (!ev_lt(&e, &h->a[p]))
            break;
        h->a[i] = h->a[p];
        i = p;
    }
    h->a[i] = e;
}

static Ev heap_pop(Heap *h) {
    Ev top = h->a[0];
    Ev last = h->a[--h->n];
    int i = 0;
    for (;;) {
        int l = 2 * i + 1, r = l + 1, m = i;
        if (l < h->n && ev_lt(&h->a[l], &last))
            m = l;
        if (r < h->n && ev_lt(&h->a[r], m == i ? &last : &h->a[l]))
            m = r;
        if (m == i)
            break;
        h->a[i] = h->a[m];
        i = m;
    }
    h->a[i] = last;
    return top;
}

/* ---------------- pack_tie (events.py) ---------------- */

static inline int64_t pack_tie(int kind, int src_host, int64_t seq) {
    int64_t variant = kind != KIND_PACKET;
    return (variant << 62) | ((int64_t)src_host << 32) | (seq & 0xffffffffLL);
}

static inline int tie_src_host(int64_t tie) {
    return (int)((tie >> 32) & ((1 << 30) - 1));
}

/* ---------------- seq unwrap (transport/header.py) ---------------- */

static inline int64_t unwrap32(int64_t near, int32_t wire) {
    uint32_t delta_u = (uint32_t)wire - (uint32_t)near + 0x80000000u;
    return near + ((int64_t)delta_u - 0x80000000LL);
}

static inline int32_t to_wire32(int64_t seq) { return (int32_t)(uint32_t)seq; }

/* ---------------- per-host netstack state ---------------- */

typedef struct {
    int64_t refill, tokens, last;
} TB;

static int64_t tb_depart(TB *tb, int64_t now, int64_t size) {
    if (tb->refill <= 0)
        return now;
    int64_t cap = tb->refill + MTU_BYTES;
    int64_t iv = now > tb->last ? (now - tb->last) / REFILL_INTERVAL_NS : 0;
    int64_t cur = tb->tokens + iv * tb->refill;
    if (cur > cap)
        cur = cap;
    int64_t cur_last = tb->last + iv * REFILL_INTERVAL_NS;
    int64_t deficit = size - cur;
    if (deficit < 0)
        deficit = 0;
    int64_t k = (deficit + tb->refill - 1) / tb->refill;
    int64_t depart;
    if (deficit > 0) {
        depart = cur_last + k * REFILL_INTERVAL_NS;
        tb->last = depart;
    } else {
        depart = now;
        tb->last = cur_last;
    }
    tb->tokens = cur + k * tb->refill - size;
    return depart;
}

typedef struct {
    int64_t first_above, drop_next;
    int64_t count;
    int dropping;
} CoDel;

#include <math.h>
static int64_t codel_control_law(int64_t count) {
    int64_t c = count < 1 ? 1 : (count > 1024 ? 1024 : count);
    return (int64_t)(CODEL_INTERVAL_NS / sqrt((double)c));
}

static int codel_dequeue(CoDel *cd, int64_t now, int64_t sojourn,
                         int64_t backlog_bytes) {
    int below = sojourn < CODEL_TARGET_NS || backlog_bytes < MTU_BYTES;
    int ok_to_drop = 0;
    if (below)
        cd->first_above = -1;
    else if (cd->first_above < 0)
        cd->first_above = now + CODEL_INTERVAL_NS;
    else if (now >= cd->first_above)
        ok_to_drop = 1;

    if (cd->dropping) {
        if (!ok_to_drop) {
            cd->dropping = 0;
            return 0;
        }
        if (now >= cd->drop_next) {
            cd->count += 1;
            cd->drop_next += codel_control_law(cd->count);
            return 1;
        }
        return 0;
    }
    if (ok_to_drop) {
        cd->dropping = 1;
        int recent = (now - cd->drop_next) < CODEL_INTERVAL_NS;
        cd->count = (recent && cd->count > 2) ? cd->count - 2 : 1;
        cd->drop_next = now + codel_control_law(cd->count);
        return 1;
    }
    return 0;
}

/* ---------------- TCP slot (cpu_ref/tcp_ref.py Slot) ---------------- */

typedef struct {
    int st;
    int lport, rport, rhost;
    int64_t snd_una, snd_nxt, snd_max, snd_end;
    int fin_pending, fin_sent;
    int64_t peer_wnd;
    int64_t rcv_nxt, rcv_fin, delivered;
    int64_t ooo[OOO_RANGES][2];
    int64_t sacked[OOO_RANGES][2];
    int64_t rtx_mark;
    int64_t cwnd, ssthresh;
    int dupacks;
    int64_t recover;
    int in_rec;
    int64_t srtt, rttvar, rto;
    int rtt_pending;
    int64_t rtt_seq, rtt_ts, rto_expire;
    int backoff;
    int64_t tev_time;
    int64_t retransmits, segs_in, segs_out;
} Slot;

static void slot_reset(Slot *s) {
    s->snd_una = 0;
    s->snd_nxt = 0;
    s->snd_max = 0;
    s->snd_end = 1;
    s->fin_pending = 0;
    s->fin_sent = 0;
    s->peer_wnd = RCV_WND;
    s->rcv_nxt = 0;
    s->rcv_fin = -1;
    s->delivered = 0;
    for (int i = 0; i < OOO_RANGES; i++) {
        s->ooo[i][0] = s->ooo[i][1] = -1;
        s->sacked[i][0] = s->sacked[i][1] = -1;
    }
    s->rtx_mark = 0;
    s->cwnd = INIT_CWND_SEGS * MSS;
    s->ssthresh = 1LL << 40;
    s->dupacks = 0;
    s->recover = 0;
    s->in_rec = 0;
    s->srtt = -1;
    s->rttvar = 0;
    s->rto = RTO_INIT;
    s->rtt_pending = 0;
    s->rtt_seq = 0;
    s->rtt_ts = 0;
    s->rto_expire = TIME_MAX;
    s->backoff = 0;
}

static void slot_init(Slot *s) {
    memset(s, 0, sizeof(*s));
    s->st = CLOSED;
    s->rhost = -1;
    slot_reset(s);
    s->tev_time = TIME_MAX;
    s->retransmits = s->segs_in = s->segs_out = 0;
}

static void rtt_update(Slot *s, int64_t rtt) {
    if (s->srtt < 0) {
        s->rttvar = rtt / 2;
        s->srtt = rtt;
    } else {
        int64_t d = s->srtt - rtt;
        if (d < 0)
            d = -d;
        s->rttvar = (3 * s->rttvar + d) / 4;
        s->srtt = (7 * s->srtt + rtt) / 8;
    }
    int64_t g = 4 * s->rttvar;
    if (g < GRANULARITY)
        g = GRANULARITY;
    int64_t rto = s->srtt + g;
    if (rto < RTO_MIN)
        rto = RTO_MIN;
    if (rto > RTO_MAX)
        rto = RTO_MAX;
    s->rto = rto;
    s->rtt_pending = 0;
}

static void ooo_absorb(Slot *s) {
    for (int pass = 0; pass < OOO_RANGES; pass++) {
        int64_t reach = -1;
        int hits[OOO_RANGES], nh = 0;
        for (int i = 0; i < OOO_RANGES; i++) {
            if (s->ooo[i][0] >= 0 && s->ooo[i][0] <= s->rcv_nxt) {
                hits[nh++] = i;
                if (s->ooo[i][1] > reach)
                    reach = s->ooo[i][1];
            }
        }
        if (reach > s->rcv_nxt)
            s->rcv_nxt = reach;
        for (int i = 0; i < nh; i++)
            s->ooo[hits[i]][0] = s->ooo[hits[i]][1] = -1;
    }
}

static void range_insert(int64_t ranges[][2], int64_t s, int64_t e) {
    int64_t ms = s, me = e;
    int overlap[OOO_RANGES], nov = 0;
    for (int i = 0; i < OOO_RANGES; i++) {
        int64_t rs = ranges[i][0], re = ranges[i][1];
        if (rs >= 0 && s <= re && e >= rs) {
            overlap[nov++] = i;
            if (rs < ms)
                ms = rs;
            if (re > me)
                me = re;
        }
    }
    int ins = -1;
    for (int i = 0; i < OOO_RANGES && ins < 0; i++) {
        int is_ov = 0;
        for (int j = 0; j < nov; j++)
            if (overlap[j] == i)
                is_ov = 1;
        if (is_ov || ranges[i][0] < 0)
            ins = i;
    }
    for (int j = 0; j < nov; j++)
        ranges[overlap[j]][0] = ranges[overlap[j]][1] = -1;
    if (ins >= 0) {
        ranges[ins][0] = ms;
        ranges[ins][1] = me;
    }
}

/* first unsacked hole above `from` per the scoreboard */
static int64_t sack_hole(int64_t sacked[][2], int64_t from) {
    int64_t hole = from;
    for (int pass = 0; pass < OOO_RANGES; pass++) {
        int64_t reach = -1;
        for (int i = 0; i < OOO_RANGES; i++) {
            int64_t rs = sacked[i][0], re = sacked[i][1];
            if (rs >= 0 && rs <= hole && hole < re && re > reach)
                reach = re;
        }
        if (reach > hole)
            hole = reach;
    }
    return hole;
}

/* ---------------- simulation world ---------------- */

typedef struct {
    int h, n_nodes, clients, servers;
    int64_t *lat;   /* [n*n] */
    float *rel;     /* [n*n] */
    Heap *queues;   /* [h] */
    int64_t *seq;   /* [h] */
    uint32_t *ctr;  /* [h] */
    Key *keys;      /* [h] */
    Slot *slots;    /* [h*NSOCK] */
    TB *tx, *rx;
    CoDel *codel;
    int64_t *rx_backlog;
    /* counters */
    int64_t events_handled, packets_sent, packets_dropped, codel_dropped;
    int64_t bytes_sent, bytes_recv;
    int64_t *streams_started, *streams_done;
    int64_t bytes_down, resets, retransmits;
    /* model params */
    int64_t resp_bytes, pause_ns, runahead_ns, bootstrap_end_ns;
    int use_netstack;
    /* outbox */
    Ev *outbox;
    int *outbox_dst;
    int outbox_n, outbox_cap;
} World;

static void outbox_add(World *w, int dst, Ev e) {
    if (w->outbox_n == w->outbox_cap) {
        w->outbox_cap = w->outbox_cap ? w->outbox_cap * 2 : 1024;
        w->outbox = realloc(w->outbox, (size_t)w->outbox_cap * sizeof(Ev));
        w->outbox_dst = realloc(w->outbox_dst, (size_t)w->outbox_cap * sizeof(int));
    }
    w->outbox_dst[w->outbox_n] = dst;
    w->outbox[w->outbox_n++] = e;
}

static void mk_seg(int32_t *data, int lport, int rport, int64_t seq,
                   int64_t ack, int flags, int64_t plen, int64_t wnd,
                   int64_t sack_s, int64_t sack_e) {
    memset(data, 0, PAYLOAD_LANES * sizeof(int32_t));
    data[LANE_PORTS] = to_wire32(((int64_t)(lport & 0xffff) << 16) | (rport & 0xffff));
    data[LANE_SEQ] = to_wire32(seq);
    data[LANE_ACK] = to_wire32(ack);
    data[LANE_FLAGS_LEN] = (int32_t)((flags & 0xff) | (plen << 8));
    data[LANE_WND] = (int32_t)wnd;
    data[LANE_SACK_S] = to_wire32(sack_s);
    data[LANE_SACK_E] = to_wire32(sack_e);
}

/* ingress relay + CoDel; returns 1 if the event reaches the model */
static int ingress(World *w, int host, Ev *e) {
    if (!w->use_netstack || e->kind != KIND_PACKET)
        return 1;
    int64_t size = e->aux & AUX_SIZE_MASK;
    if (e->aux & AUX_SHAPED_BIT) {
        w->rx_backlog[host] -= size;
        w->bytes_recv += size;
        return 1;
    }
    int src = tie_src_host(e->tie);
    if (src == host || e->time < w->bootstrap_end_ns || w->rx[host].refill <= 0) {
        w->bytes_recv += size;
        return 1;
    }
    TB *tb = &w->rx[host];
    int64_t tok0 = tb->tokens, last0 = tb->last;
    int64_t ready = tb_depart(tb, e->time, size);
    int64_t sojourn = ready - e->time;
    if (codel_dequeue(&w->codel[host], ready, sojourn, w->rx_backlog[host])) {
        tb->tokens = tok0;
        tb->last = last0;
        w->codel_dropped++;
        return 0;
    }
    if (ready > e->time) {
        w->rx_backlog[host] += size;
        Ev d = *e;
        d.time = ready;
        d.aux = (int32_t)(size | AUX_SHAPED_BIT);
        heap_push(&w->queues[host], d);
        return 0;
    }
    w->bytes_recv += size;
    return 1;
}

typedef struct {
    int used;
    int dst;
    int32_t data[PAYLOAD_LANES];
    int64_t size;
} PLane;

typedef struct {
    int used;
    int64_t time;
    int kind;
    int slot;
} LLane;

static void handle(World *w, int host, Ev *e, int64_t window_end) {
    if (!ingress(w, host, e))
        return;
    w->events_handled++;
    Slot *slots = &w->slots[(size_t)host * NSOCK];
    int64_t t = e->time;
    int kind = e->kind;
    int32_t *data = e->data;

    /* ---- app_pre (tgen client stream start) ---- */
    int is_client = host < w->clients;
    int is_server = !is_client && host < w->clients + w->servers;
    int m_start = (kind == KIND_STREAM_START) && is_client;
    int can = 0, app_mask = 0, app_slot = 0;
    if (m_start) {
        int cslot = -1;
        for (int i = 0; i < NSOCK && cslot < 0; i++)
            if (slots[i].st == CLOSED)
                cslot = i;
        if (cslot >= 0) {
            can = 1;
            int lport = 40000 + (int)(w->streams_started[host] % 20000);
            int server = w->clients +
                         (int)((host + w->streams_started[host]) % w->servers);
            Slot *s = &slots[cslot];
            /* app_connect from CLOSED */
            slot_reset(s);
            s->st = SYNSENT;
            s->lport = lport;
            s->rport = TGEN_PORT;
            s->rhost = server;
            s->snd_end += REQ_BYTES; /* app_write */
            w->streams_started[host]++;
            app_mask = 1;
            app_slot = cslot;
        }
    }
    int64_t bytes_before = 0;
    for (int i = 0; i < NSOCK; i++)
        bytes_before += slots[i].delivered;

    LLane l_lanes[LOCAL_LANES];
    PLane p_lanes[PACKET_LANES];
    memset(l_lanes, 0, sizeof(l_lanes));
    memset(p_lanes, 0, sizeof(p_lanes));

    int m_rx = kind == KIND_PACKET;
    int m_tmr = kind == KIND_TCP_TIMER;
    int m_flush = kind == KIND_TCP_FLUSH;

    int sig_est = 0, sig_fin = 0, sig_closed = 0, sig_rst = 0;
    int need_ack = 0, rtx_hole = 0, m_act = 0, m_stray = 0;
    Slot *act = NULL;
    int act_i = 0;
    int32_t stray_rst[PAYLOAD_LANES];
    int src = tie_src_host(e->tie);

    if (m_rx) {
        int sport = (data[LANE_PORTS] >> 16) & 0xffff;
        int dport = data[LANE_PORTS] & 0xffff;
        int flags = data[LANE_FLAGS_LEN] & 0xff;
        int64_t plen = ((int64_t)(uint32_t)data[LANE_FLAGS_LEN] >> 8) & 0xffffff;
        int64_t wnd = data[LANE_WND];
        int f_syn = !!(flags & FLAG_SYN), f_ack = !!(flags & FLAG_ACK);
        int f_fin = !!(flags & FLAG_FIN), f_rst = !!(flags & FLAG_RST);

        int rx_exact_i = -1, rx_lsn_i = -1;
        for (int i = 0; i < NSOCK; i++) {
            Slot *s = &slots[i];
            if (rx_exact_i < 0 && s->st != CLOSED && s->st != LISTEN &&
                s->lport == dport && s->rhost == src && s->rport == sport)
                rx_exact_i = i;
            if (rx_lsn_i < 0 && s->st == LISTEN && s->lport == dport)
                rx_lsn_i = i;
        }
        int rx_listen = rx_exact_i < 0 && rx_lsn_i >= 0;
        int rx_match = rx_exact_i >= 0 || rx_lsn_i >= 0;

        int m_spawn = 0;
        if (rx_listen && f_syn && !f_ack) {
            int child_i = -1;
            for (int i = 0; i < NSOCK && child_i < 0; i++)
                if (slots[i].st == CLOSED)
                    child_i = i;
            if (child_i >= 0) {
                m_spawn = 1;
                Slot *cs = &slots[child_i];
                slot_reset(cs);
                cs->st = SYNRECEIVED;
                cs->lport = dport;
                cs->rport = sport;
                cs->rhost = src;
                cs->rcv_nxt = 1;
                cs->peer_wnd = wnd;
                act = cs;
                act_i = child_i;
            }
        }
        if (rx_exact_i >= 0) {
            act = &slots[rx_exact_i];
            act_i = rx_exact_i;
        }
        m_act = (rx_exact_i >= 0) || m_spawn;
        if (m_act) {
            Slot *v = act;
            v->segs_in++;
            int64_t abs_seq = unwrap32(v->rcv_nxt, data[LANE_SEQ]);
            int64_t abs_ack = unwrap32(v->snd_una, data[LANE_ACK]);

            int m_rst = f_rst && v->st != CLOSED;
            if (m_rst) {
                v->st = CLOSED;
                v->rto_expire = TIME_MAX;
                sig_rst = 1;
            }
            int live = !m_rst;

            if (live && v->st == SYNSENT && f_syn && f_ack && abs_ack >= 1) {
                v->st = ESTABLISHED;
                v->rcv_nxt = 1;
                v->snd_una = 1;
                v->peer_wnd = wnd;
                v->rto_expire = TIME_MAX;
                v->backoff = 0;
                if (v->rtt_pending)
                    rtt_update(v, t - v->rtt_ts);
                sig_est = 1;
                need_ack = 1;
            } else if (live && v->st == SYNRECEIVED && f_ack && !f_syn &&
                       abs_ack >= 1) {
                v->st = ESTABLISHED;
                if (v->snd_una < 1)
                    v->snd_una = 1;
                v->peer_wnd = wnd;
                v->rto_expire = TIME_MAX;
                v->backoff = 0;
                if (v->rtt_pending)
                    rtt_update(v, t - v->rtt_ts);
                sig_est = 1;
            }

            int datast = v->st == ESTABLISHED || v->st == FINWAIT1 ||
                         v->st == FINWAIT2 || v->st == CLOSING ||
                         v->st == TIMEWAIT || v->st == CLOSEWAIT ||
                         v->st == LASTACK;
            int m_data_st = live && datast;

            /* ---- ACK processing ---- */
            int m_ackp = m_data_st && f_ack;
            int64_t snd_una_pre = v->snd_una;
            int valid_ack = m_ackp && v->snd_una < abs_ack && abs_ack <= v->snd_max;
            int64_t acked = valid_ack ? abs_ack - v->snd_una : 0;
            if (valid_ack && v->rtt_pending && abs_ack >= v->rtt_seq)
                rtt_update(v, t - v->rtt_ts);
            int full_ack = valid_ack && v->in_rec && abs_ack >= v->recover;
            int part_ack = valid_ack && v->in_rec && !full_ack;
            int ss = valid_ack && !v->in_rec && v->cwnd < v->ssthresh;
            int ca = valid_ack && !v->in_rec && !ss;
            int64_t cwnd1 = ss ? v->cwnd + (acked < MSS ? acked : MSS) : v->cwnd;
            if (ca) {
                int64_t denom = cwnd1 > 1 ? cwnd1 : 1;
                int64_t inc = (int64_t)MSS * MSS / denom;
                cwnd1 += inc > 1 ? inc : 1;
            }
            if (full_ack)
                cwnd1 = v->ssthresh;
            if (part_ack) {
                cwnd1 = cwnd1 - acked + MSS;
                if (cwnd1 < MSS)
                    cwnd1 = MSS;
            }
            rtx_hole = part_ack;
            if (valid_ack) {
                v->snd_una = abs_ack;
                if (v->snd_nxt < abs_ack)
                    v->snd_nxt = abs_ack;
                v->dupacks = 0;
                v->backoff = 0;
            }
            if (full_ack)
                v->in_rec = 0;
            v->cwnd = cwnd1;
            if (m_ackp)
                v->peer_wnd = wnd;
            int outstanding = v->snd_una < v->snd_max;
            if (valid_ack)
                v->rto_expire = outstanding ? t + v->rto : TIME_MAX;

            if (USE_SACK) {
                int32_t ss_w = data[LANE_SACK_S], se_w = data[LANE_SACK_E];
                if (m_ackp && ss_w != se_w)
                    range_insert(v->sacked, unwrap32(v->snd_una, ss_w),
                                 unwrap32(v->snd_una, se_w));
                if (m_ackp)
                    for (int i = 0; i < OOO_RANGES; i++)
                        if (v->sacked[i][0] >= 0 && v->sacked[i][1] <= v->snd_una)
                            v->sacked[i][0] = v->sacked[i][1] = -1;
            }

            int dup = m_ackp && !valid_ack && abs_ack == snd_una_pre &&
                      plen == 0 && !f_fin && outstanding;
            int dup3 = dup && v->dupacks == 2 && !v->in_rec;
            int64_t flight = v->snd_max - v->snd_una;
            if (dup)
                v->dupacks++;
            if (dup3) {
                int64_t th = flight / 2;
                if (th < 2 * MSS)
                    th = 2 * MSS;
                v->ssthresh = th;
                v->cwnd = th + 3 * MSS;
                v->recover = v->snd_max;
                v->in_rec = 1;
            } else if (dup && v->in_rec) {
                v->cwnd += MSS;
            }
            if (USE_SACK) {
                int64_t hole_rx = sack_hole(v->sacked, v->snd_una);
                int sack_any = 0;
                for (int i = 0; i < OOO_RANGES; i++)
                    if (v->sacked[i][0] >= 0)
                        sack_any = 1;
                int march = dup && v->in_rec && sack_any &&
                            hole_rx > v->rtx_mark && hole_rx < v->snd_max;
                rtx_hole = rtx_hole || dup3 || march;
                if (full_ack)
                    v->rtx_mark = 0;
                else if (rtx_hole)
                    v->rtx_mark = hole_rx;
            } else {
                rtx_hole = rtx_hole || dup3;
            }

            int fin_acked = m_ackp && v->fin_sent && v->snd_una >= v->snd_end + 1;
            if (fin_acked) {
                if (v->st == FINWAIT1)
                    v->st = FINWAIT2;
                else if (v->st == CLOSING)
                    v->st = TIMEWAIT;
                else if (v->st == LASTACK)
                    v->st = CLOSED;
            }
            sig_closed = sig_closed || (fin_acked && v->st == CLOSED);
            int enter_tw_ack = fin_acked && v->st == TIMEWAIT;

            /* ---- in-window data ---- */
            int m_seg = m_data_st && plen > 0;
            int64_t seg_s = abs_seq, seg_e = abs_seq + plen;
            int acceptable = m_seg && seg_e > v->rcv_nxt &&
                             seg_s <= v->rcv_nxt + RCV_WND;
            int in_order = acceptable && seg_s <= v->rcv_nxt;
            int ooo_seg = acceptable && !in_order;
            int64_t old_rcv = v->rcv_nxt;
            if (in_order) {
                v->rcv_nxt = seg_e;
                ooo_absorb(v);
            }
            if (ooo_seg)
                range_insert(v->ooo, seg_s, seg_e);
            if (m_seg) {
                v->delivered += v->rcv_nxt - old_rcv;
                need_ack = 1;
            }

            /* ---- peer FIN ---- */
            int m_finp = m_data_st && f_fin;
            if (m_finp && v->rcv_fin < 0)
                v->rcv_fin = seg_e;
            int fin_now = m_data_st && v->rcv_fin >= 0 && v->rcv_nxt == v->rcv_fin;
            int enter_tw_fin = 0;
            if (fin_now) {
                v->rcv_nxt++;
                if (v->st == ESTABLISHED)
                    v->st = CLOSEWAIT;
                else if (v->st == FINWAIT2) {
                    enter_tw_fin = 1;
                    v->st = TIMEWAIT;
                } else if (v->st == FINWAIT1)
                    v->st = CLOSING;
                sig_fin = 1;
            }
            if (m_finp)
                need_ack = 1;
            if (enter_tw_ack || enter_tw_fin)
                v->rto_expire = t + 1 * NS_PER_SEC; /* TGEN_TCP timewait */
        } else if (!rx_match && !f_rst) {
            m_stray = 1;
            int64_t ack_for = unwrap32(0, data[LANE_ACK]);
            int64_t abs_seq0 = unwrap32(0, data[LANE_SEQ]);
            mk_seg(stray_rst, dport, sport, ack_for,
                   abs_seq0 + plen + (f_syn ? 1 : 0) + (f_fin ? 1 : 0),
                   FLAG_RST | FLAG_ACK, 0, 0, 0, 0);
        }
    }

    if (m_tmr) {
        int t_slot = data[0];
        if (t_slot < 0)
            t_slot = 0;
        if (t_slot > NSOCK - 1)
            t_slot = NSOCK - 1;
        Slot *sw = &slots[t_slot];
        if (t >= sw->tev_time)
            sw->tev_time = TIME_MAX;
        int fired = t >= sw->rto_expire && sw->rto_expire < TIME_MAX;
        if (fired && sw->st == TIMEWAIT) {
            sw->st = CLOSED;
            sw->rto_expire = TIME_MAX;
            sig_closed = 1;
        } else if (fired && sw->snd_una < sw->snd_max) {
            int64_t flight_w = sw->snd_max - sw->snd_una;
            int64_t th = flight_w / 2;
            if (th < 2 * MSS)
                th = 2 * MSS;
            sw->ssthresh = th;
            sw->cwnd = MSS;
            sw->snd_nxt = sw->snd_una;
            sw->in_rec = 0;
            sw->dupacks = 0;
            sw->rto = sw->rto * 2 < RTO_MAX ? sw->rto * 2 : RTO_MAX;
            sw->backoff++;
            sw->rtt_pending = 0;
            sw->rto_expire = TIME_MAX;
            if (USE_SACK) {
                for (int i = 0; i < OOO_RANGES; i++)
                    sw->sacked[i][0] = sw->sacked[i][1] = -1;
                sw->rtx_mark = 0;
            }
        }
    }

    /* ---------------- OUTPUT pass ---------------- */
    int out_i;
    if (m_act)
        out_i = act_i;
    else if (m_tmr || m_flush) {
        out_i = data[0];
        if (out_i < 0)
            out_i = 0;
        if (out_i > NSOCK - 1)
            out_i = NSOCK - 1;
    } else
        out_i = app_slot;
    int out_mask = m_act || m_tmr || m_flush || app_mask;
    rtx_hole = rtx_hole && m_act;

    if (out_mask) {
        Slot *o = &slots[out_i];
        int m_syn_out = (o->st == SYNSENT || o->st == SYNRECEIVED) && o->snd_nxt == 0;
        int syn_flags = o->st == SYNRECEIVED ? (FLAG_SYN | FLAG_ACK) : FLAG_SYN;
        int syn_is_rtx = m_syn_out && o->snd_max > 0;
        int can_send = o->st == ESTABLISHED || o->st == CLOSEWAIT ||
                       o->st == FINWAIT1 || o->st == CLOSING || o->st == LASTACK;
        int64_t cwin = o->cwnd < o->peer_wnd ? o->cwnd : o->peer_wnd;
        int64_t wnd_lim = o->snd_una + cwin;
        int64_t fin_lim = o->snd_end + (o->fin_pending ? 1 : 0);

        int64_t hole = USE_SACK ? sack_hole(o->sacked, o->snd_una) : o->snd_una;
        int is_first_rtx = rtx_hole && can_send;
        int64_t cursor = is_first_rtx ? hole : o->snd_nxt;
        if (is_first_rtx)
            o->rtt_pending = 0; /* Karn */
        int sent_any = 0, fin_goes = 0;
        int64_t rtx_count = 0;

        for (int i = 0; i < SEGS_PER_FLUSH; i++) {
            int64_t room = o->snd_end;
            if (wnd_lim < room)
                room = wnd_lim;
            if (cursor + MSS < room)
                room = cursor + MSS;
            int64_t dlen = room - cursor;
            if (dlen < 0)
                dlen = 0;
            int send_data = can_send && dlen > 0;
            int send_fin = can_send && !send_data && o->fin_pending &&
                           cursor == o->snd_end && cursor + 1 <= wnd_lim &&
                           !fin_goes;
            int lane_used = send_data || send_fin;
            int64_t seq_w = cursor;
            int lflags = send_fin ? (FLAG_FIN | FLAG_ACK)
                                  : (send_data ? FLAG_ACK : 0);
            if (i == 0 && m_syn_out) {
                lane_used = 1;
                seq_w = 0;
                lflags = syn_flags;
            }
            int64_t lplen = send_data ? dlen : 0;
            if (lane_used) {
                p_lanes[i].used = 1;
                p_lanes[i].dst = o->rhost;
                mk_seg(p_lanes[i].data, o->lport, o->rport, seq_w, o->rcv_nxt,
                       lflags, lplen, RCV_WND, 0, 0);
                p_lanes[i].size = lplen + HDR_BYTES;
            }
            int is_rtx = send_data && cursor < o->snd_max;
            if (i == 0)
                is_rtx = is_rtx || is_first_rtx || syn_is_rtx;
            rtx_count += is_rtx ? 1 : 0;
            int fresh = send_data && cursor >= o->snd_max && !is_rtx;
            if (fresh && !o->rtt_pending) {
                o->rtt_pending = 1;
                o->rtt_seq = cursor + dlen;
                o->rtt_ts = t;
            }
            cursor += (send_data ? dlen : 0) + (send_fin ? 1 : 0);
            if (i == 0 && is_first_rtx && cursor < o->snd_nxt)
                cursor = o->snd_nxt;
            fin_goes = fin_goes || send_fin;
            sent_any = sent_any || lane_used;
        }

        if (can_send && o->snd_nxt < cursor)
            o->snd_nxt = cursor;
        if (m_syn_out)
            o->snd_nxt = 1;
        if (o->snd_max < o->snd_nxt)
            o->snd_max = o->snd_nxt;
        if (fin_goes) {
            if (o->st == ESTABLISHED)
                o->st = FINWAIT1;
            else if (o->st == CLOSEWAIT)
                o->st = LASTACK;
        }
        if (m_syn_out && !o->rtt_pending && !syn_is_rtx) {
            o->rtt_pending = 1;
            o->rtt_seq = 1;
            o->rtt_ts = t;
        }
        int outstanding_o = (o->snd_una < o->snd_max) || m_syn_out;
        if (outstanding_o && o->rto_expire >= TIME_MAX && (sent_any || m_syn_out))
            o->rto_expire = t + o->rto;
        int64_t lim = fin_lim < wnd_lim ? fin_lim : wnd_lim;
        int more = can_send && lim > cursor;
        int need_tev = o->rto_expire < o->tev_time;
        if (need_tev)
            o->tev_time = o->rto_expire;
        if (fin_goes)
            o->fin_sent = 1;
        o->retransmits += rtx_count;
        w->retransmits += rtx_count;
        for (int i = 0; i < SEGS_PER_FLUSH; i++)
            o->segs_out += p_lanes[i].used;

        if (more) {
            l_lanes[0].used = 1;
            l_lanes[0].time = t;
            l_lanes[0].kind = KIND_TCP_FLUSH;
            l_lanes[0].slot = out_i;
        }
        if (need_tev) {
            l_lanes[1].used = 1;
            l_lanes[1].time = o->rto_expire;
            l_lanes[1].kind = KIND_TCP_TIMER;
            l_lanes[1].slot = out_i;
        }
    }

    /* control lane (ACK / stray RST) */
    if (m_act && need_ack) {
        Slot *va = &slots[act_i];
        int64_t ss = 0, se = 0;
        if (USE_SACK) {
            int64_t bs = -1, be = -1;
            for (int i = 0; i < OOO_RANGES; i++) {
                int64_t rs = va->ooo[i][0], re = va->ooo[i][1];
                if (rs >= 0 && (bs < 0 || rs < bs || (rs == bs && re < be))) {
                    bs = rs;
                    be = re;
                }
            }
            if (bs >= 0) {
                ss = bs;
                se = be;
            }
        }
        PLane *pl = &p_lanes[SEGS_PER_FLUSH];
        pl->used = 1;
        pl->dst = va->rhost;
        mk_seg(pl->data, va->lport, va->rport, va->snd_nxt, va->rcv_nxt,
               FLAG_ACK, 0, RCV_WND, ss, se);
        pl->size = HDR_BYTES;
    } else if (m_stray) {
        PLane *pl = &p_lanes[SEGS_PER_FLUSH];
        pl->used = 1;
        pl->dst = src;
        memcpy(pl->data, stray_rst, sizeof(stray_rst));
        pl->size = HDR_BYTES;
    }

    /* ---- app_post (tgen) ---- */
    {
        int sig_slot = out_mask ? out_i : -1;
        int sslot = sig_slot >= 0 ? sig_slot : 0;
        Slot *v = &slots[sslot];
        int m_resp = is_server && sig_slot >= 0 && v->st == ESTABLISHED &&
                     v->delivered >= REQ_BYTES && v->snd_end == 1;
        if (m_resp) {
            /* app_write + app_close */
            if (v->st != CLOSED && v->st != LISTEN && !v->fin_pending)
                v->snd_end += w->resp_bytes;
            if (v->st != CLOSED && v->st != LISTEN)
                v->fin_pending = 1;
        }
        int m_eof = sig_fin && is_client;
        if (m_eof && v->st != CLOSED && v->st != LISTEN)
            v->fin_pending = 1;
        int m_done = sig_closed && is_client;
        if (m_done)
            w->streams_done[host]++;
        if (is_client) {
            int64_t now_del = 0;
            for (int i = 0; i < NSOCK; i++)
                now_del += slots[i].delivered;
            w->bytes_down += now_del - bytes_before;
        }
        if (sig_rst)
            w->resets++;
        if (m_resp || m_eof) {
            l_lanes[2].used = 1;
            l_lanes[2].time = t;
            l_lanes[2].kind = KIND_TCP_FLUSH;
            l_lanes[2].slot = sslot;
        }
        if (m_done || (m_start && !can)) {
            l_lanes[3].used = 1;
            l_lanes[3].time = t + w->pause_ns;
            l_lanes[3].kind = KIND_STREAM_START;
            l_lanes[3].slot = 0;
        }
    }

    /* ---- engine wrap: seq minting, egress, loss ---- */
    uint32_t base_ctr = w->ctr[host];
    for (int li = 0; li < LOCAL_LANES; li++) {
        if (!l_lanes[li].used)
            continue;
        Ev le;
        memset(&le, 0, sizeof(le));
        le.time = l_lanes[li].time;
        le.kind = l_lanes[li].kind;
        le.tie = pack_tie(le.kind, host, w->seq[host]++);
        le.data[0] = l_lanes[li].slot;
        heap_push(&w->queues[host], le);
    }
    int hnode = host % w->n_nodes;
    for (int pi = 0; pi < PACKET_LANES; pi++) {
        if (!p_lanes[pi].used)
            continue;
        int dst = p_lanes[pi].dst;
        if (dst < 0)
            dst = 0;
        if (dst > w->h - 1)
            dst = w->h - 1;
        int dnode = dst % w->n_nodes;
        int64_t lat = w->lat[hnode * w->n_nodes + dnode];
        float rel = w->rel[hnode * w->n_nodes + dnode];
        float loss_u = uniform_f32(fold_in(w->keys[host], base_ctr + (uint32_t)pi));
        if (lat >= TIME_MAX)
            continue;
        int64_t dep = t;
        if (w->use_netstack) {
            int exempt = dst == host || t < w->bootstrap_end_ns;
            if (!exempt)
                dep = tb_depart(&w->tx[host], t, p_lanes[pi].size);
        }
        if (loss_u < rel) {
            int64_t deliver = dep + lat;
            if (deliver < window_end)
                deliver = window_end;
            Ev pe;
            memset(&pe, 0, sizeof(pe));
            pe.time = deliver;
            pe.kind = KIND_PACKET;
            pe.tie = pack_tie(KIND_PACKET, host, w->seq[host]++);
            memcpy(pe.data, p_lanes[pi].data, sizeof(pe.data));
            pe.aux = (int32_t)(p_lanes[pi].size & AUX_SIZE_MASK);
            outbox_add(w, dst, pe);
            w->packets_sent++;
            if (w->use_netstack)
                w->bytes_sent += p_lanes[pi].size;
        } else {
            w->packets_dropped++;
        }
    }
    w->ctr[host] = base_ctr + PACKET_LANES;
}

int main(int argc, char **argv) {
    if (argc < 4) {
        fprintf(stderr, "usage: %s TABLES H SIM_NS [SEED] [RESP] [PAUSE] "
                        "[RUNAHEAD] [TX_REFILL] [RX_REFILL]\n", argv[0]);
        return 2;
    }
    World w;
    memset(&w, 0, sizeof(w));
    FILE *f = fopen(argv[1], "rb");
    if (!f) {
        perror("tables");
        return 2;
    }
    int32_t n;
    if (fread(&n, 4, 1, f) != 1)
        return 2;
    w.n_nodes = n;
    w.lat = malloc((size_t)n * n * 8);
    w.rel = malloc((size_t)n * n * 4);
    if (fread(w.lat, 8, (size_t)n * n, f) != (size_t)n * n)
        return 2;
    if (fread(w.rel, 4, (size_t)n * n, f) != (size_t)n * n)
        return 2;
    fclose(f);

    w.h = atoi(argv[2]);
    int64_t end_ns = atoll(argv[3]);
    int64_t seed = argc > 4 ? atoll(argv[4]) : 7;
    w.resp_bytes = argc > 5 ? atoll(argv[5]) : 100000;
    w.pause_ns = argc > 6 ? atoll(argv[6]) : 500 * NS_PER_MS;
    w.runahead_ns = argc > 7 ? atoll(argv[7]) : 2 * NS_PER_MS;
    int64_t tx_refill = argc > 8 ? atoll(argv[8]) : 12500; /* 100 Mbit */
    int64_t rx_refill = argc > 9 ? atoll(argv[9]) : 12500;
    w.use_netstack = 1;
    w.clients = w.h / 2;
    w.servers = w.h - w.clients;

    w.queues = calloc((size_t)w.h, sizeof(Heap));
    w.seq = calloc((size_t)w.h, 8);
    w.ctr = calloc((size_t)w.h, 4);
    w.keys = malloc((size_t)w.h * sizeof(Key));
    w.slots = malloc((size_t)w.h * NSOCK * sizeof(Slot));
    w.tx = malloc((size_t)w.h * sizeof(TB));
    w.rx = malloc((size_t)w.h * sizeof(TB));
    w.codel = malloc((size_t)w.h * sizeof(CoDel));
    w.rx_backlog = calloc((size_t)w.h, 8);
    w.streams_started = calloc((size_t)w.h, 8);
    w.streams_done = calloc((size_t)w.h, 8);

    Key base = {(uint32_t)((uint64_t)seed >> 32), (uint32_t)seed};
    for (int i = 0; i < w.h; i++) {
        w.keys[i] = fold_in(base, (uint32_t)i);
        for (int sck = 0; sck < NSOCK; sck++)
            slot_init(&w.slots[(size_t)i * NSOCK + sck]);
        w.tx[i].refill = tx_refill;
        w.tx[i].tokens = tx_refill + MTU_BYTES;
        w.tx[i].last = 0;
        w.rx[i].refill = rx_refill;
        w.rx[i].tokens = rx_refill + MTU_BYTES;
        w.rx[i].last = 0;
        w.codel[i].first_above = -1;
        w.codel[i].drop_next = 0;
        w.codel[i].count = 0;
        w.codel[i].dropping = 0;
    }
    /* tgen init: servers listen on slot 0; clients bootstrap a stream start */
    for (int i = w.clients; i < w.clients + w.servers; i++) {
        Slot *s = &w.slots[(size_t)i * NSOCK];
        s->st = LISTEN;
        s->lport = TGEN_PORT;
    }
    for (int i = 0; i < w.clients; i++) {
        Ev e;
        memset(&e, 0, sizeof(e));
        e.time = START_NS;
        e.kind = KIND_STREAM_START;
        e.tie = pack_tie(KIND_STREAM_START, i, w.seq[i]++);
        heap_push(&w.queues[i], e);
    }

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);

    /* the conservative window loop (engine/round.py run_until semantics) */
    for (;;) {
        int64_t start = TIME_MAX;
        for (int i = 0; i < w.h; i++)
            if (w.queues[i].n && w.queues[i].a[0].time < start)
                start = w.queues[i].a[0].time;
        if (start >= end_ns)
            break;
        int64_t window_end = start + w.runahead_ns;
        if (window_end > end_ns)
            window_end = end_ns;
        w.outbox_n = 0;
        for (int i = 0; i < w.h; i++) {
            Heap *q = &w.queues[i];
            while (q->n && q->a[0].time < window_end) {
                Ev e = heap_pop(q);
                handle(&w, i, &e, window_end);
            }
        }
        for (int k = 0; k < w.outbox_n; k++)
            heap_push(&w.queues[w.outbox_dst[k]], w.outbox[k]);
    }

    clock_gettime(CLOCK_MONOTONIC, &t1);
    double wall = (double)(t1.tv_sec - t0.tv_sec) + (double)(t1.tv_nsec - t0.tv_nsec) / 1e9;
    int64_t sdone = 0, sstarted = 0;
    for (int i = 0; i < w.h; i++) {
        sdone += w.streams_done[i];
        sstarted += w.streams_started[i];
    }
    printf("{\"backend\": \"native-c\", \"hosts\": %d, \"sim_s\": %.6f, "
           "\"wall_s\": %.4f, \"rate\": %.6f, \"events\": %lld, "
           "\"streams_started\": %lld, \"streams_done\": %lld, "
           "\"bytes_down\": %lld, \"packets_sent\": %lld, "
           "\"packets_dropped\": %lld, \"codel_dropped\": %lld, "
           "\"retransmits\": %lld, \"resets\": %lld, "
           "\"bytes_sent\": %lld, \"bytes_recv\": %lld}\n",
           w.h, (double)end_ns / 1e9, wall, (double)end_ns / 1e9 / wall,
           (long long)w.events_handled, (long long)sstarted,
           (long long)sdone, (long long)w.bytes_down,
           (long long)w.packets_sent, (long long)w.packets_dropped,
           (long long)w.codel_dropped, (long long)w.retransmits,
           (long long)w.resets, (long long)w.bytes_sent,
           (long long)w.bytes_recv);
    return 0;
}
