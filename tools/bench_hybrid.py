"""Managed-tier benchmark: N-pair C HTTP client/server matrix under the
hybrid scheduler (guests on sharded CPU kernel workers, packets on the
device engine). The managed-scale counterpart of bench.py's scripted tgen
metric (round-2 verdict item 1).

  python tools/bench_hybrid.py [pairs] [workers] [fetches] [nbytes]

Prints one JSON line: guests, syscalls, wall_s, sim-s/wall-s, fetches.
On this image wall-clock parallel speedup is bounded by the single CPU
core — the workers exist for correctness + scaling shape; run on a
multi-core host for the real curve.
"""

import json
import pathlib
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

NS_PER_MS = 1_000_000
NS_PER_SEC = 1_000_000_000


def main():
    pairs = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    fetches = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    nbytes = int(sys.argv[4]) if len(sys.argv) > 4 else 20_000

    from shadow_tpu.engine import EngineConfig
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.hostk.kernel import ProcessSpec
    from shadow_tpu.runtime.hybrid import ParallelHybridScheduler

    src = pathlib.Path(__file__).resolve().parent.parent / "examples" / "http-matrix"
    build = pathlib.Path(tempfile.mkdtemp(prefix="httpm-"))
    bins = {}
    for name in ("http_server", "http_client"):
        dst = build / name
        subprocess.run(["cc", "-O2", "-o", str(dst), str(src / f"{name}.c")], check=True)
        bins[name] = str(dst)

    # two-site topology, 10 ms apart, 1 ms self-latency (the round window)
    graph = NetworkGraph.from_gml(
        """graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" ]
]"""
    )
    host_names = [f"server{i}" for i in range(pairs)] + [f"client{i}" for i in range(pairs)]
    host_nodes = [0] * pairs + [1] * pairs
    tables = compute_routing(graph).with_hosts(host_nodes)
    W = graph.min_latency_ns()
    cfg = EngineConfig(
        num_hosts=2 * pairs,
        queue_capacity=256,
        outbox_capacity=64,
        runahead_ns=W,
        seed=7,
    )
    specs = []
    for i in range(pairs):
        specs.append(
            ProcessSpec(
                host=f"server{i}",
                args=[bins["http_server"], "8080", str(nbytes), str(fetches)],
            )
        )
        specs.append(
            ProcessSpec(
                host=f"client{i}",
                args=[bins["http_client"], f"server{i}", "8080", str(fetches)],
                start_ns=(50 + (i % 200)) * NS_PER_MS,  # staggered start
            )
        )

    sched = ParallelHybridScheduler(
        tables,
        cfg,
        host_names=host_names,
        host_nodes=host_nodes,
        specs=specs,
        num_workers=workers,
        seed=7,
        data_dir=build / "data",
    )
    sim_sec = 30
    t0 = time.perf_counter()
    try:
        try:
            sched.run(sim_sec * NS_PER_SEC)
        finally:
            sched.shutdown()
        wall = time.perf_counter() - t0
        stats = sched.stats()
        info = sched.proc_info()
    finally:
        sched.close()

    ok = sum(
        1
        for p in info
        if p["host"].startswith("client") and f"fetched {fetches}/{fetches}".encode() in p["stdout"]
    )
    print(
        json.dumps(
            {
                "metric": f"hybrid_http_{2*pairs}guests_syscalls_per_wall_sec",
                "guests": 2 * pairs,
                "workers": workers,
                "clients_ok": ok,
                "clients": pairs,
                "syscalls": stats["syscalls_handled"],
                "packets": stats["packets_sent"],
                "device_passes": sched.device_passes,
                "phase_wall": {k: round(v, 3) for k, v in getattr(sched, "phase_wall", {}).items()},
                "wall_s": round(wall, 2),
                "syscalls_per_s": int(stats["syscalls_handled"] / wall),
                "sim_s_per_wall_s": round(sim_sec / wall, 4),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
