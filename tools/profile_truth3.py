"""De-noised stage timings: 64 inner reps per call so the ~100 ms (+-20)
tunnel floor cannot swamp per-stage deltas. Fresh inputs per call.

  python tools/profile_truth3.py [hosts]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    reps = 3
    N = 64

    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import (
        _next_window_end,
        flush_outbox,
        handle_one_iteration,
        handle_one_iteration_compact,
        run_round,
    )

    cfg, model, tables, st0 = _build(hosts)
    we_far = jnp.asarray(10**18, jnp.int64)

    warm = jax.jit(
        lambda s: run_round(
            s, _next_window_end(s, we_far, cfg, None), model, tables, cfg
        )
    )
    st = st0
    for _ in range(3):
        st = warm(st)
    jax.block_until_ready(st.events_handled)
    results = {"backend": jax.default_backend(), "hosts": hosts, "n_inner": N}

    def timed(name, fn, n_inner=N):
        f = jax.jit(fn)
        out = f(st, jnp.uint32(999))
        jax.block_until_ready(out)
        ts = []
        for r in range(reps):
            t0 = time.perf_counter()
            out = f(st, jnp.uint32(r))
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        best = min(ts)
        results[name] = {
            "total_ms": round(best * 1e3, 1),
            "per_ms": round(best * 1e3 / n_inner, 3),
        }
        print(name, results[name], flush=True)

    # floor reference
    timed("floor", lambda s, r: s.events_handled.sum() + r, n_inner=1)

    # true all-in per-round cost: N real rounds in one call
    def rounds_n(s, r):
        s = s.replace(seq=s.seq + r * 0)

        def one(s, _):
            we = _next_window_end(s, we_far, cfg, None)
            return run_round(s, we, model, tables, cfg), None

        s, _ = jax.lax.scan(one, s, None, length=N)
        return s.events_handled.sum() + r

    timed("round_allin", rounds_n)

    # flush at various deliver_lanes
    def mk_flush(lanes):
        c2 = dataclasses.replace(cfg, deliver_lanes=lanes)

        def f(s, r):
            s = s.replace(seq=s.seq + r * 0)

            def step(q, _):
                s2 = flush_outbox(s.replace(queue=q), None, c2)
                return s2.queue, None

            q, _ = jax.lax.scan(step, s.queue, None, length=N)
            return q.count.sum() + q.tie.sum() + r

        return f

    for lanes in (64, 32):
        timed(f"flush_d{lanes}", mk_flush(lanes))

    # bodies
    we = jnp.asarray(int(np.asarray(st.now)) + 10**15, jnp.int64)

    def mk_body(fn):
        def f(s, r):
            s = s.replace(seq=s.seq + r * 0)

            def inner(s, _):
                return fn(s), None

            s, _ = jax.lax.scan(inner, s, None, length=N)
            return s.events_handled.sum() + r

        return f

    timed("body_full", mk_body(lambda s: handle_one_iteration(s, we, model, tables, cfg)))
    for lanes in (256, 1024):
        timed(
            f"body_compact{lanes}",
            mk_body(
                lambda s, L=lanes: handle_one_iteration_compact(
                    s, we, model, tables, cfg, L
                )
            ),
        )

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
