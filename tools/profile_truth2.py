"""Stage-level ground truth (fresh inputs per call; see profile_truth.py).

Splits the two dominant costs found by profile_truth:
  flush (~16 ms): S1 sort vs S2 sort vs the deliver_lanes-wide
      push_self_lanes merge, at deliver_lanes {32, 64}
  body (~2-3.5 ms): full model vs identity handler (queue mechanics
      only) vs compacted widths {512, 2048}

Also times the call floor with a scalar-only argument (is the 116 ms
floor per-call or per-argument-bytes?).

  python tools/profile_truth2.py [hosts] [reps]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5

    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu import equeue
    from shadow_tpu.engine.round import (
        _next_window_end,
        flush_outbox,
        handle_one_iteration,
        handle_one_iteration_compact,
        run_round,
    )
    from shadow_tpu.events import KIND_PACKET
    from shadow_tpu.simtime import TIME_MAX

    cfg, model, tables, st0 = _build(hosts)
    we_far = jnp.asarray(10**18, jnp.int64)

    warm = jax.jit(
        lambda s: run_round(
            s, _next_window_end(s, we_far, cfg, None), model, tables, cfg
        )
    )
    st = st0
    for _ in range(3):
        st = warm(st)
    jax.block_until_ready(st.events_handled)
    results = {"backend": jax.default_backend(), "hosts": hosts}

    def timed(name, fn, n_inner=1):
        f = jax.jit(fn)
        out = f(st, jnp.uint32(999))
        jax.block_until_ready(out)
        ts = []
        for r in range(reps):
            t0 = time.perf_counter()
            out = f(st, jnp.uint32(r))
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        best = min(ts)
        results[name] = round(best * 1e3, 3)
        print(name, results[name], "ms", flush=True)

    # --- call floor with scalar-only args ---
    g = jax.jit(lambda r: r * 2 + 1)
    jax.block_until_ready(g(jnp.uint32(1)))
    ts = []
    for r in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(g(jnp.uint32(r + 2)))
        ts.append(time.perf_counter() - t0)
    results["call_floor_scalar"] = round(min(ts) * 1e3, 3)
    print("call_floor_scalar", results["call_floor_scalar"], "ms", flush=True)

    # --- flush internals (N=8 inner reps inside one call) ---
    N = 8

    ob = st.outbox
    h_local, o_cap = ob.valid.shape
    m = h_local * o_cap

    def flat(x):
        return x.reshape((m,) + x.shape[2:])

    def mk_flush(lanes):
        c2 = dataclasses.replace(cfg, deliver_lanes=lanes)

        def f(s, r):
            s = s.replace(seq=s.seq + r * 0)

            def step(q, _):
                s2 = flush_outbox(s.replace(queue=q), None, c2)
                return s2.queue, None

            q, _ = jax.lax.scan(step, s.queue, None, length=N)
            return q.count.sum() + r

        return f

    timed("flush8_d64", mk_flush(64), n_inner=N)
    timed("flush8_d32", mk_flush(32), n_inner=N)

    def s1_only(s, r):
        ob = s.outbox
        valid, dst = flat(ob.valid), flat(ob.dst)
        time_, tie = flat(ob.time), flat(ob.tie)
        data, aux = flat(ob.data), flat(ob.aux)
        kind = jnp.full(valid.shape, KIND_PACKET, jnp.int32)

        def step(c, _):
            key1 = jnp.where(valid, dst + c * 0, hosts).astype(jnp.int32)
            outs = jax.lax.sort(
                (key1, time_, tie, kind, aux, valid)
                + tuple(data[:, i] for i in range(data.shape[1])),
                num_keys=1,
                is_stable=True,
            )
            return c + outs[0][0], None

        c, _ = jax.lax.scan(step, r.astype(jnp.int32), None, length=N)
        return c

    timed("sort15op_m8", s1_only, n_inner=N)

    def mk_merge(lanes):
        d = lanes
        gshape = (h_local, d)

        def f(s, r):
            g_valid = jnp.zeros(gshape, bool).at[:, 0].set(True)
            g_time = jnp.full(gshape, 5, jnp.int64)
            g_tie = jnp.zeros(gshape, jnp.int64)
            g_kind = jnp.full(gshape, KIND_PACKET, jnp.int32)
            g_aux = jnp.zeros(gshape, jnp.int32)
            g_data = jnp.zeros(gshape + (data_lanes,), jnp.int32)

            def step(q, _):
                q2 = equeue.push_self_lanes(
                    q, valid=g_valid, time=g_time + q.count[0], tie=g_tie,
                    kind=g_kind, data=g_data, aux=g_aux,
                )
                return q2, None

            q, _ = jax.lax.scan(step, s.queue, None, length=N)
            return q.count.sum() + q.tie.sum() + q.time.sum() + r

        return f

    data_lanes = st.queue.data.shape[2]
    timed("merge8_d64", mk_merge(64), n_inner=N)
    timed("merge8_d32", mk_merge(32), n_inner=N)

    # --- body internals ---
    we = jnp.asarray(int(np.asarray(st.now)) + 10**15, jnp.int64)

    class _IdModel:
        """Identity handler: pops happen, nothing is emitted."""

        DRAWS_PER_EVENT = 0
        BOOTSTRAP_DRAWS = 0
        LOCAL_EMITS = 1
        PACKET_EMITS = 1
        LOSS_COUNTER_LANE = None

        def handle(self, mstate, ev, draw, c, host_id):
            from shadow_tpu.engine.state import (
                empty_local_emits,
                empty_packet_emits,
            )

            h = host_id.shape[0]
            return mstate, empty_local_emits(h, 1), empty_packet_emits(h, 1)

    idm = _IdModel()

    def mk_body(n, fn):
        def f(s, r):
            s = s.replace(seq=s.seq + r * 0)

            def inner(s, _):
                return fn(s), None

            s, _ = jax.lax.scan(inner, s, None, length=n)
            return s.events_handled.sum() + r

        return f

    timed(
        "body8_full",
        mk_body(8, lambda s: handle_one_iteration(s, we, model, tables, cfg)),
        n_inner=8,
    )
    timed(
        "body8_idmodel",
        mk_body(8, lambda s: handle_one_iteration(s, we, idm, tables, cfg)),
        n_inner=8,
    )
    for lanes in (512, 2048):
        timed(
            f"body8_compact{lanes}",
            mk_body(
                8,
                lambda s, L=lanes: handle_one_iteration_compact(
                    s, we, model, tables, cfg, L
                ),
            ),
            n_inner=8,
        )

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
