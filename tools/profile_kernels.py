"""Kernel-count vs width, measured per-iteration engine costs, and
chunk-driver dispatch accounting.

Part 1 (width scan): compile the plain iteration body at several host
widths on the live backend, print optimized-HLO fusion/kernel counts and
fresh-input timings. If time is ~flat in width while kernel count is
constant, the body is launch-bound and the lever is fewer kernels.

Part 2 (engine comparison, round-6 verdict Next #3): measure the
per-iteration cost of all three round engines — plain (one-event-per-host
handler), pump (XLA microscan, engine/pump.py) and megakernel (fused
Pallas launch, engine/megakernel.py) — on the bench workload's burst
phase. All three are bit-identical, so the comparison starts every
engine from the same mid-burst state and divides wall time by the
drain-loop iterations actually executed (SimState.iters_done). The
resulting table is the one published in docs/megakernel.md.

Part 3 (dispatch pipeline, round-7 tentpole): on the same burst phase,
measure the dispatch gap — wall time between a chunk completing on
device and the next chunk's launch — for the synchronous driver shape
(block on the probe, run the old _peek_next_time decision, then launch)
vs the depth-2 pipelined driver (launch N+1 BEFORE fetching N's probe:
the gap collapses to zero because the next chunk is already queued when
completion is even observable). Also reports per-chunk HBM copy bytes
from the compiled chunk's memory analysis with and without state
donation: donated runs alias the whole SimState in place
(aliased_bytes ~= state size, copied_bytes ~= the probe).

Part 4 (checkpoint, robustness round): save/restore wall + bytes.

Part 5 (ensemble round): amortized per-replica launch cost vs replica
count R — wall-clock per replica at R=1/8/32 through the vmapped
ensemble driver (docs/ensemble.md).

Part 6 (sweep-scheduler round, docs/service.md): cold-compile vs
cache-hit dispatch wall for the fingerprint-keyed compile cache — the
AOT compile a world's FIRST batch pays, the ~free executable lookup
every later same-shape batch pays, and one cached-chunk dispatch — plus
amortized per-job wall vs sweep size (1/2/4/8 jobs through the
production SweepService).

Part 7 (adaptive-window round, docs/architecture.md "Lookahead &
compaction"): on a sparse-in-time scenario (hosts whose true lookahead
is 20x the graph's minimum latency), the drain-iteration reduction and
window-widening the adaptive LBTS bound buys vs fixed-width rounds —
per-run window-width distribution (log10 histogram of per-chunk mean
live widths), live-lane occupancy per drain iteration (the quantity
live-host compaction exploits), and the same run again under
active-lane compaction. The iteration-reduction factor printed here is
the published acceptance number for the adaptive-window round.

Part 9 (event-exchange v2 round, docs/parallelism.md "Segment
exchange"): per-phase cost of the round-boundary exchange — pool sort /
collective exchange / queue landing / capacity check — dense lane grid
vs sort-based segment exchange on the same busy staged outbox, plus
sharded per-round collective deltas and analytic bytes/host (dense
heuristic buckets vs the segment ring at measured exch_hwm capacity).

  python tools/profile_kernels.py [reps] [engine_hosts]

Env knobs: SHADOW_TPU_PROFILE_WIDTHS (comma list, part 1),
SHADOW_TPU_PROFILE_BURST_MS (start,end sim-ms for parts 2-3, default 20,60).
"""

import json
import os
import re
import sys
import time

sys.path.insert(0, ".")


def _fusion_count(compiled_text: str) -> int:
    return len(re.findall(r"^\s*(fusion|%fusion)", compiled_text, re.M))


def profile_widths(reps: int):
    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import handle_one_iteration

    default_widths = (
        "1280,10240" if jax.default_backend() == "tpu" else "640,1280"
    )
    widths_env = os.environ.get("SHADOW_TPU_PROFILE_WIDTHS", default_widths)
    widths = [int(x) for x in widths_env.split(",") if x.strip()]
    we = jnp.asarray(10**15, jnp.int64)
    out = {}
    for hosts in widths:
        cfg, model, tables, st0 = _build(hosts)
        f = jax.jit(lambda s: handle_one_iteration(s, we, model, tables, cfg))
        compiled = f.lower(st0).compile()
        txt = compiled.as_text()
        # fresh-input timing
        st = f(st0)
        jax.block_until_ready(st.events_handled)
        ts = []
        for r in range(reps):
            s_in = st0.replace(rng_counter=st0.rng_counter + r + 1)
            jax.block_until_ready(s_in.rng_counter)
            t0 = time.perf_counter()
            o = f(s_in)
            jax.block_until_ready(o.events_handled)
            ts.append(time.perf_counter() - t0)
        out[hosts] = {
            "fusions": _fusion_count(txt),
            "hlo_lines": txt.count("\n"),
            "best_ms": round(min(ts) * 1e3, 2),
        }
        print(hosts, out[hosts], flush=True)
    return out


def profile_engines(reps: int, hosts: int):
    """Per-iteration cost of plain vs pump vs megakernel on the burst
    phase: identical start state (the engines are bit-identical, so any
    engine may produce it), wall divided by drain-loop iterations."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import run_round, run_until

    burst_env = os.environ.get("SHADOW_TPU_PROFILE_BURST_MS", "20,60")
    b0_ms, b1_ms = [int(x) for x in burst_env.split(",")]
    b0, b1 = b0_ms * 1_000_000, b1_ms * 1_000_000

    cfg0, model, tables, st0 = _build(hosts)
    st_burst = run_until(st0, b0, model, tables, cfg0, rounds_per_chunk=32)
    jax.block_until_ready(st_burst.events_handled)
    iters0 = int(np.asarray(st_burst.iters_done).sum())
    ev0 = int(np.asarray(st_burst.events_handled).sum())

    variants = {
        "plain": dataclasses.replace(cfg0, engine="plain", pump_k=0),
        "pump": dataclasses.replace(cfg0, engine="pump", pump_k=8),
        "megakernel": dataclasses.replace(
            cfg0, engine="megakernel", pump_k=8
        ),
    }
    out = {}
    for name, cfg in variants.items():
        row = {}
        try:
            we = jnp.asarray(b0 + cfg.runahead_ns, jnp.int64)
            body = jax.jit(
                lambda s, c=cfg: run_round(s, we, model, tables, c)
            )
            row["fusions"] = _fusion_count(
                body.lower(st_burst).compile().as_text()
            )
            # warm the chunked executable, then time the burst window
            s = run_until(
                st_burst, b1, model, tables, cfg, rounds_per_chunk=32
            )
            jax.block_until_ready(s.events_handled)
            walls = []
            for _ in range(reps):
                t0 = time.perf_counter()
                s = run_until(
                    st_burst, b1, model, tables, cfg, rounds_per_chunk=32
                )
                jax.block_until_ready(s.events_handled)
                walls.append(time.perf_counter() - t0)
            wall = min(walls)
            iters = int(np.asarray(s.iters_done).sum()) - iters0
            events = int(np.asarray(s.events_handled).sum()) - ev0
            row.update(
                wall_s=round(wall, 3),
                iters=iters,
                events=events,
                us_per_iter=round(wall / max(iters, 1) * 1e6, 1),
                ns_per_event=round(wall / max(events, 1) * 1e9, 1),
            )
        except Exception as e:  # noqa: BLE001 — a backend that cannot
            # lower one engine must not kill the comparison of the others
            row["error"] = str(e)[:300]
        out[name] = row
        print(json.dumps({"engine": name, **row}), flush=True)
    if "us_per_iter" in out.get("plain", {}):
        for name in ("pump", "megakernel"):
            if "us_per_iter" in out.get(name, {}):
                out[name]["iter_cost_vs_plain"] = round(
                    out[name]["us_per_iter"] / out["plain"]["us_per_iter"], 3
                )
    return out


def profile_dispatch(hosts: int, chunks: int = 6):
    """Dispatch accounting on the burst phase, read from the tracker
    plane's spans (round-8 tentpole): the REAL run_until driver runs
    with a utils/tracker.py Tracker attached — the same spans
    `--trace-file` writes — and the sync decision gap / pipelined
    launch-ahead margin / per-launch call wall are computed from the
    recorded (ts, dur) intervals instead of an ad-hoc reimplementation
    of the drive loop. Also reports per-chunk HBM copy bytes (donated
    vs undonated chunk executable)."""
    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import _run_chunk, _run_chunk_jit, run_until
    from shadow_tpu.utils.tracker import Tracker

    burst_env = os.environ.get("SHADOW_TPU_PROFILE_BURST_MS", "20,60")
    b0_ms = int(burst_env.split(",")[0])
    b0 = b0_ms * 1_000_000

    cfg, model, tables, st0 = _build(hosts)
    st_burst = run_until(st0, b0, model, tables, cfg, rounds_per_chunk=32)
    jax.block_until_ready(st_burst.events_handled)
    far = 10**15  # far horizon: chunks never quiesce
    end = jnp.asarray(far, jnp.int64)
    rpc = 8
    out = {"hosts": hosts, "rounds_per_chunk": rpc, "chunks": chunks}

    # --- per-chunk HBM copy bytes, before/after donation -----------------
    def _nbytes(leaf):
        try:
            return leaf.nbytes
        except Exception:  # typed PRNG key arrays: measure the raw words
            return jax.random.key_data(leaf).nbytes

    out["state_bytes"] = int(sum(_nbytes(l) for l in jax.tree.leaves(st_burst)))
    try:
        plain = jax.jit(_run_chunk, static_argnums=(2, 3, 5))
        rows = {}
        for name, fn in (("no_donate", plain), ("donate", _run_chunk_jit)):
            ma = (
                fn.lower(st_burst, end, rpc, model, tables, cfg)
                .compile()
                .memory_analysis()
            )
            rows[name] = {
                "output_bytes": int(ma.output_size_in_bytes),
                "aliased_bytes": int(ma.alias_size_in_bytes),
                "copied_bytes": int(
                    ma.output_size_in_bytes - ma.alias_size_in_bytes
                ),
            }
        out["per_chunk_copy"] = rows
    except Exception as e:  # noqa: BLE001 — memory analysis is best-effort
        out["per_chunk_copy"] = {"error": str(e)[:200]}

    # --- dispatch gap from tracker spans ---------------------------------
    def drive(pipeline):
        """Run exactly `chunks` launches through the production driver
        with a Tracker attached; the bounded max_chunks stop raises
        RuntimeError by design (the horizon is unreachable). Only THAT
        stop is absorbed — a CapacityError or any other runtime failure
        must surface, not publish gap numbers from a dead run."""
        tr = Tracker()
        try:
            run_until(
                st_burst, far, model, tables, cfg, rounds_per_chunk=rpc,
                max_chunks=chunks, pipeline=pipeline, tracker=tr,
            )
        except RuntimeError as e:
            if "did not reach end_time" not in str(e):
                raise  # capacity/donation/backend errors are real
        launches = {
            e.get("args", {}).get("chunk"): e
            for e in tr.spans("compile+launch") + tr.spans("chunk_launch")
        }
        fetches = {
            e.get("args", {}).get("chunk"): e for e in tr.spans("probe_fetch")
        }
        return tr, launches, fetches

    def _span_end(e):
        return e["ts"] + e["dur"]

    drive(True)  # warm the chunk executable (its spans are discarded)
    _tr, launches, fetches = drive(False)
    # synchronous driver: the device idles from probe-fetch end (chunk N
    # observed done, decision made) to the next launch call — plus the
    # launch call itself (reported separately: XLA:CPU executes inline
    # during dispatch, which would otherwise masquerade as decision time)
    gaps = [
        (launches[i + 1]["ts"] - _span_end(fetches[i])) / 1e3
        for i in range(chunks - 1)
        if i + 1 in launches and i in fetches
    ]
    dwalls = [launches[i]["dur"] / 1e3 for i in launches if i > 0]
    out["dispatch_gap_sync_ms"] = {
        "mean": round(sum(gaps) / max(len(gaps), 1), 3),
        "max": round(max(gaps), 3),
        "launch_call_mean_ms": round(sum(dwalls) / max(len(dwalls), 1), 3),
    }
    _tr, launches, fetches = drive(True)
    # pipelined: chunk N+1's launch span ENDS before chunk N's probe
    # fetch does — the gap is 0 by construction; the measured quantity is
    # the launch-ahead margin (how long before chunk N's completion was
    # even observable the next chunk was already dispatched)
    ahead = [
        (_span_end(fetches[i]) - _span_end(launches[i + 1])) / 1e3
        for i in range(chunks - 1)
        if i + 1 in launches and i in fetches
    ]
    dwalls = [launches[i]["dur"] / 1e3 for i in launches if i > 0]
    out["dispatch_gap_pipelined_ms"] = {
        "by_construction": 0.0,
        "launch_ahead_mean_ms": round(sum(ahead) / max(len(ahead), 1), 3),
        "launch_call_mean_ms": round(sum(dwalls) / max(len(dwalls), 1), 3),
    }
    print(json.dumps({"dispatch": out}), flush=True)
    return out


def profile_checkpoint(hosts: int, reps: int = 3):
    """Part 4 (robustness round): wall time and bytes of a checkpoint
    save (state_to_host bulk fetch + atomic npz write) and restore (npz
    read + state_from_host upload), on a mid-burst state — the cost a
    --checkpoint-interval cadence actually pays per checkpoint, and the
    transfer the rollback-and-regrow retainer pays per snapshot. Also
    verifies the restore is leaf-exact."""
    import tempfile

    import jax
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import run_until
    from shadow_tpu.engine.state import (
        _is_key_leaf,
        state_from_host,
        state_to_host,
    )
    from shadow_tpu.runtime.checkpoint import load_checkpoint, save_checkpoint

    burst_env = os.environ.get("SHADOW_TPU_PROFILE_BURST_MS", "20,60")
    b0 = int(burst_env.split(",")[0]) * 1_000_000

    cfg, model, tables, st0 = _build(hosts)
    st = run_until(st0, b0, model, tables, cfg, rounds_per_chunk=32)
    jax.block_until_ready(st.events_handled)

    def _nbytes(leaf):
        try:
            return leaf.nbytes
        except Exception:
            return jax.random.key_data(leaf).nbytes

    out = {
        "hosts": hosts,
        "state_bytes": int(sum(_nbytes(l) for l in jax.tree.leaves(st))),
        "leaves": len(jax.tree.leaves(st)),
    }
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt.npz")
        fetch_ms, save_ms, load_ms = [], [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            host = state_to_host(st)
            fetch_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            save_checkpoint(path, host, {"fingerprint": "profile"})
            save_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            restored, _meta = load_checkpoint(path, st)
            jax.block_until_ready(restored.events_handled)
            load_ms.append((time.perf_counter() - t0) * 1e3)
        out["file_bytes"] = int(os.path.getsize(path))
        out["fetch_ms"] = round(min(fetch_ms), 2)
        out["save_ms"] = round(min(save_ms), 2)
        out["restore_ms"] = round(min(load_ms), 2)
        host = state_to_host(st)
        rt = state_from_host(host, st)
        out["roundtrip_exact"] = bool(
            all(
                np.array_equal(
                    np.asarray(jax.random.key_data(a) if _is_key_leaf(a) else a),
                    np.asarray(jax.random.key_data(b) if _is_key_leaf(b) else b),
                )
                for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(rt))
            )
        )
    print(json.dumps({"checkpoint": out}), flush=True)
    return out


def profile_ensemble(reps: int = 3, hosts: int = 0, replica_counts=(1, 8, 32)):
    """Part 5 (ensemble round): amortized per-replica cost vs R. The
    ensemble plane's claim is that stacking R replicas under one vmap
    amortizes the per-chunk dispatch/launch overhead (flat in R) across
    R worlds — so wall-clock PER REPLICA falls as R grows until compute
    saturates the backend. Measured on a small phold world (dispatch-
    bound by construction), with the production run_ensemble_until
    driver and a Tracker attached: per-R rows report total wall, wall
    per replica, the chunk-launch span total, and launch wall per
    replica (the directly-amortized component)."""
    import time

    import jax
    import jax.numpy as jnp  # noqa: F401 — backend init ordering
    import numpy as np

    from shadow_tpu.engine import EngineConfig
    from shadow_tpu.engine.ensemble import init_ensemble_state, run_ensemble_until
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models import PholdModel
    from shadow_tpu.simtime import NS_PER_MS
    from shadow_tpu.utils.tracker import Tracker

    h = hosts or (1024 if jax.default_backend() == "tpu" else 128)
    n_nodes = 8
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} ]")
        lines.append(f'  edge [ source {i} target {i} latency "1 ms" ]')
        lines.append(
            f'  edge [ source {i} target {(i + 1) % n_nodes} latency "3 ms" ]'
        )
    lines.append("]")
    graph = NetworkGraph.from_gml("\n".join(lines))
    tables = compute_routing(graph).with_hosts([i % n_nodes for i in range(h)])
    cfg = EngineConfig(
        num_hosts=h, runahead_ns=graph.min_latency_ns(), seed=7
    )
    model = PholdModel(
        num_hosts=h, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
    )
    end = 100 * NS_PER_MS
    out = {"hosts": h, "sim_ms": 100, "rows": {}}
    base_per_replica = None
    for r_count in replica_counts:
        row = {}
        try:
            ens0 = init_ensemble_state(cfg, model, r_count)
            # compile (fresh executable per R: the batch shape changed)
            t0 = time.perf_counter()
            s = run_ensemble_until(ens0, end, model, tables, cfg, rounds_per_chunk=16)
            jax.block_until_ready(s.events_handled)
            row["compile_plus_run_s"] = round(time.perf_counter() - t0, 3)
            walls = []
            tr = Tracker()
            for _ in range(reps):
                t0 = time.perf_counter()
                s = run_ensemble_until(
                    ens0, end, model, tables, cfg,
                    rounds_per_chunk=16, tracker=tr,
                )
                jax.block_until_ready(s.events_handled)
                walls.append(time.perf_counter() - t0)
            wall = min(walls)
            launch_s = tr.phase_totals().get("chunk_launch", 0.0) / reps
            row.update(
                wall_s=round(wall, 4),
                wall_per_replica_ms=round(wall / r_count * 1e3, 2),
                launch_wall_s=round(launch_s, 4),
                launch_per_replica_ms=round(launch_s / r_count * 1e3, 3),
                events=int(np.asarray(s.events_handled).sum()),
            )
            if base_per_replica is None:
                base_per_replica = wall / r_count
            else:
                row["speedup_per_replica_vs_r1"] = round(
                    base_per_replica / (wall / r_count), 2
                )
        except Exception as e:  # noqa: BLE001 — one R failing (e.g. OOM at
            # 32 on a small backend) must not kill the smaller rows
            row["error"] = str(e)[:300]
        out["rows"][r_count] = row
        print(json.dumps({"ensemble_r": r_count, **row}), flush=True)
    return out


def profile_sweep(hosts: int = 0, capacity: int = 4):
    """Part 6 (sweep-scheduler round): what the compile cache buys.

    Cold vs hit: the first batch of a distinct world pays one AOT
    compile (lower_ensemble_chunk + .compile(), a CompileCache miss);
    every later same-shape batch acquires the executable from the cache
    (a dict lookup) — measured against one cached-chunk dispatch wall so
    the saving is in context. Then sweeps of 1/2/4/8 jobs run through
    the production SweepService and report wall per job: the amortized
    per-job overhead the service's packing + caching exist to shrink."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from shadow_tpu.config.sweep import load_sweep_spec
    from shadow_tpu.engine import EngineConfig
    from shadow_tpu.engine.ensemble import (
        ensemble_engine_cfg,
        init_ensemble_state,
        lower_ensemble_chunk,
    )
    from shadow_tpu.engine.state import trace_static_cfg
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models import PholdModel
    from shadow_tpu.runtime.compile_cache import CompileCache
    from shadow_tpu.runtime.sweep import SweepService
    from shadow_tpu.simtime import NS_PER_MS

    h = hosts or (1024 if jax.default_backend() == "tpu" else 128)
    graph = NetworkGraph.from_gml(
        "graph [\n  directed 0\n"
        + "".join(
            f"  node [ id {i} ]\n"
            f'  edge [ source {i} target {i} latency "1 ms" ]\n'
            f'  edge [ source {i} target {(i + 1) % 8} latency "3 ms" ]\n'
            for i in range(8)
        )
        + "]"
    )
    tables = compute_routing(graph).with_hosts([i % 8 for i in range(h)])
    cfg = EngineConfig(num_hosts=h, runahead_ns=graph.min_latency_ns(), seed=7)
    model = PholdModel(
        num_hosts=h, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
    )
    end, rpc = 100 * NS_PER_MS, 16
    out = {"hosts": h, "capacity": capacity}

    # --- cold compile vs cache hit ---------------------------------------
    cache = CompileCache()
    ens0 = init_ensemble_state(cfg, model, capacity)
    static = trace_static_cfg(ensemble_engine_cfg(cfg))

    def build():
        return lower_ensemble_chunk(ens0, end, rpc, model, tables, cfg).compile()

    t0 = time.perf_counter()
    exe = cache.get("world", ens0, static, build)
    out["cold_compile_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    exe = cache.get("world", ens0, static, build)
    out["cache_hit_lookup_s"] = round(time.perf_counter() - t0, 6)
    st = ens0.donatable()
    end_arr = jnp.asarray(end, jnp.int64)
    st, probe = exe(st, end_arr, tables)  # warm dispatch (donates st)
    jax.block_until_ready(probe)
    st2 = ens0.donatable()
    t0 = time.perf_counter()
    st2, probe = exe(st2, end_arr, tables)
    jax.block_until_ready(probe)
    out["cached_chunk_dispatch_s"] = round(time.perf_counter() - t0, 4)
    assert cache.misses == 1 and cache.hits == 1

    # --- amortized per-job overhead vs sweep size ------------------------
    base = {
        "general": {"stop_time": "100 ms", "heartbeat_interval": None},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"rounds_per_chunk": rpc},
        "hosts": {
            "peer": {
                "network_node_id": 0,
                "quantity": h,
                "processes": [
                    {
                        "path": "phold",
                        "args": {"min_delay": "1 ms", "max_delay": "8 ms"},
                    }
                ],
            }
        },
    }
    rows = []
    for jobs in (1, 2, 4, 8):
        with tempfile.TemporaryDirectory() as d:
            spec = load_sweep_spec(
                {
                    "sweep": {
                        "config": base,
                        "output_dir": os.path.join(d, "out"),
                        "capacity": capacity,
                        "jobs": [{"name": "ph", "seed_range": [0, jobs]}],
                    }
                }
            )
            svc = SweepService(spec)
            t0 = time.perf_counter()
            manifest = svc.run()
            wall = time.perf_counter() - t0
        rows.append(
            {
                "jobs": jobs,
                "wall_s": round(wall, 3),
                "wall_per_job_s": round(wall / jobs, 3),
                "compiles": manifest["compile_cache"]["compiles"],
                "cache_hits": manifest["compile_cache"]["hits"],
            }
        )
        print(json.dumps({"sweep_size": rows[-1]}), flush=True)
    out["per_sweep_size"] = rows
    print(json.dumps({"sweep": out}), flush=True)
    return out


def profile_adaptivity(hosts: int = 0):
    """Part 7 (adaptive-window round): what the LBTS window + compaction
    buy on a sparse-in-time world.

    Topology: hosts sit on nodes with 20 ms links, while a pair of
    host-less nodes carries the graph's 1 ms minimum-latency edge — so
    the FIXED conservative width is 1 ms although every host's true
    lookahead is 20 ms. phold with delays up to 50 ms makes event times
    sparse. The three runs are leaf-identical
    (tests/test_adaptive_window.py); only the round structure differs:

      fixed            adaptive_window=False — 1 ms windows, most empty
      adaptive         window_end = min(next_event + lookahead)
      adaptive_compact adaptive + active-lane compaction (gathered
                       [H/8]-row iterations)

    Reported per run: drain iterations, live/idle round split, mean live
    window width + its log10 per-chunk histogram, live-lane occupancy
    per iteration, wall. `iter_reduction` (fixed/adaptive iterations) is
    the published acceptance number."""
    import dataclasses

    import jax
    import numpy as np

    from bench import WidthCapture
    from shadow_tpu.engine import EngineConfig, init_state
    from shadow_tpu.engine.round import (
        ChunkProbe,
        bootstrap,
        run_until,
        state_probe,
    )
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models import PholdModel
    from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

    h = hosts or (2560 if jax.default_backend() == "tpu" else 256)
    graph = NetworkGraph.from_gml(
        "\n".join(
            [
                "graph [",
                "  directed 0",
                *[f"  node [ id {i} ]" for i in range(4)],
                '  edge [ source 0 target 0 latency "20 ms" ]',
                '  edge [ source 1 target 1 latency "20 ms" ]',
                '  edge [ source 0 target 1 latency "20 ms" ]',
                '  edge [ source 2 target 3 latency "1 ms" ]',
                '  edge [ source 2 target 2 latency "1 ms" ]',
                '  edge [ source 3 target 3 latency "1 ms" ]',
                "]",
            ]
        )
    )
    tables = compute_routing(graph).with_hosts([i % 2 for i in range(h)])
    cfg0 = EngineConfig(
        num_hosts=h,
        queue_capacity=32,
        runahead_ns=graph.min_latency_ns(),
        seed=9,
        tracker=True,
    )
    model = PholdModel(
        num_hosts=h, min_delay_ns=1 * NS_PER_MS, max_delay_ns=50 * NS_PER_MS
    )
    st0 = bootstrap(init_state(cfg0, model.init()), model, cfg0)
    end = int(0.4 * NS_PER_SEC)

    def run_one(cfg):
        widths = WidthCapture()

        t0 = time.perf_counter()
        st = run_until(
            st0, end, model, tables, cfg, rounds_per_chunk=8,
            on_chunk=widths.update,
        )
        wall = time.perf_counter() - t0
        p = ChunkProbe.from_array(np.asarray(jax.jit(state_probe)(st)))
        return p, {
            "iters": p.iters,
            "rounds": {"live": p.rounds_live, "idle": p.rounds_idle},
            "window_ns_mean": round(p.window_ns_mean, 1),
            "window_ns_hist": widths.hist(),
            "occupancy": round(p.occupancy(h), 4),
            "events": p.events_handled,
            "wall_s": round(wall, 3),
        }

    out = {"hosts": h, "sim_s": end / NS_PER_SEC}
    pf, out["fixed"] = run_one(
        dataclasses.replace(cfg0, adaptive_window=False)
    )
    pa, out["adaptive"] = run_one(cfg0)
    _, out["adaptive_compact"] = run_one(
        dataclasses.replace(cfg0, active_lanes=max(h // 8, 8))
    )
    assert pa.events_handled == pf.events_handled  # leaf-identical runs
    out["iter_reduction"] = round(pf.iters / max(pa.iters, 1), 2)
    print(json.dumps({"adaptivity": out}), flush=True)
    return out


def profile_mesh_collectives(hosts: int = 0, sim_s: float = 0.1):
    """Part 8 (2-D mesh round, docs/parallelism.md "2-D mesh"): the
    per-round cost of the host-axis collectives vs shard count.

    The same single-replica phold world runs through the mesh chunk
    path (engine/mesh.py, 1xS grids) at every shard count that divides
    the visible devices; S=1 has no collectives at all, so the
    per-live-round wall delta vs the S=1 row IS the window-pmin +
    exchange-all_gather cost at that shard count (plus shard_map
    overheads — exactly the bundle a round pays). Trajectories are
    leaf-identical across S (tests/test_mesh.py), so rounds_live is the
    shared denominator. Also prints each grid's compile wall — the
    quantity the --autotune mesh-shape probe now projects (a
    single-device probe would report the S=1 column for every grid)."""
    import jax
    import numpy as np

    from shadow_tpu.engine import EngineConfig, init_state
    from shadow_tpu.engine.mesh import MeshPlan, init_mesh_state, run_mesh_until
    from shadow_tpu.engine.round import bootstrap
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models import PholdModel
    from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

    ndev = jax.device_count()
    h = hosts or (10240 if jax.default_backend() == "tpu" else 512)
    h -= h % ndev  # every shard count below must divide evenly
    graph = NetworkGraph.from_gml(
        "\n".join(
            [
                "graph [",
                "  directed 0",
                *[f"  node [ id {i} ]" for i in range(4)],
                *[
                    f'  edge [ source {i} target {i} latency "1 ms" ]'
                    for i in range(4)
                ],
                *[
                    f'  edge [ source {i} target {j} latency "3 ms" ]'
                    for i in range(4)
                    for j in range(i + 1, 4)
                ],
                "]",
            ]
        )
    )
    tables = compute_routing(graph).with_hosts([i % 4 for i in range(h)])
    cfg = EngineConfig(
        num_hosts=h,
        runahead_ns=graph.min_latency_ns(),
        seed=13,
        tracker=True,
    )
    model = PholdModel(
        num_hosts=h, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
    )
    end = int(sim_s * NS_PER_SEC)
    shard_counts = [s for s in (1, 2, 4, 8, 16) if s <= ndev and ndev % s == 0]
    out = {"hosts": h, "sim_s": sim_s, "devices": ndev, "rows": []}
    base_per_round = None
    for s_count in shard_counts:
        plan = MeshPlan(replicas=1, shards=s_count, rows=1)
        row = {"shards": s_count}
        try:
            st0 = init_mesh_state(cfg, model, plan)
            t0 = time.perf_counter()
            st = run_mesh_until(
                st0, end, model, tables, cfg, plan, rounds_per_chunk=16
            )
            jax.block_until_ready(st.events_handled)
            row["compile_plus_run_s"] = round(time.perf_counter() - t0, 3)
            st0 = init_mesh_state(cfg, model, plan)
            t0 = time.perf_counter()
            st = run_mesh_until(
                st0, end, model, tables, cfg, plan, rounds_per_chunk=16
            )
            jax.block_until_ready(st.events_handled)
            wall = time.perf_counter() - t0
            rounds_live = int(np.asarray(st.tracker.rounds_live).max())
            per_round_ms = wall / max(rounds_live, 1) * 1e3
            row.update(
                wall_s=round(wall, 4),
                rounds_live=rounds_live,
                per_round_ms=round(per_round_ms, 3),
                compile_s=round(row["compile_plus_run_s"] - wall, 3),
            )
            if s_count == 1:
                # the baseline is the collective-FREE row specifically —
                # an errored S=1 must not silently shift it to S=2
                base_per_round = per_round_ms
            elif base_per_round is not None:
                row["collective_ms_per_round"] = round(
                    per_round_ms - base_per_round, 3
                )
        except Exception as e:  # noqa: BLE001 — publish the rows that ran
            row["error"] = str(e)[:300]
        out["rows"].append(row)
        print(json.dumps({"mesh_collectives_row": row}), flush=True)
    return out


def profile_exchange(hosts: int = 0, reps: int = 10):
    """Part 9 (event-exchange v2 round, docs/parallelism.md "Segment
    exchange"): per-phase cost of the round-boundary exchange — pool
    sort / collective exchange / queue landing / capacity check — for
    the dense lane grid vs the sort-based segment exchange.

    Single-device, the phases are timed as separately-jitted stages on
    the SAME busy staged outbox (a few handler iterations with the
    flush withheld):

      * sort — the segment pool compaction (one stable (dst, time, tie)
        multi-operand sort over the flattened outbox). The dense path
        has no standalone pre-sort: its three [H, lanes]-grid sorts live
        inside the landing, which is exactly the cost the segment
        layout removes.
      * landing — equeue.push_many_sorted (dense grid) vs
        equeue.push_many_segment (ragged segments) on the staged pool.
      * capacity-check — the driver's per-chunk _peek_capacity fetch
        ([5] scalars; mode-independent — segment just feeds the
        exchange-hwm lane the pool occupancy the per-round check uses).
      * full — the whole _flush_outbox_traffic per mode, the number the
        bench exchange trial publishes.

    Sharded (every visible device), the collective phase is isolated
    mesh-collectives-style: the per-live-round wall of the sharded run
    minus the single-device run of the same mode ≈ collective +
    shard_map overhead per round (trajectories are leaf-identical, so
    rounds_live is a shared denominator). Bytes/host per round are
    analytic from the static bucket shapes (dense heuristic buckets vs
    the segment ring at the measured exch_hwm capacity)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _event_slot_bytes
    from shadow_tpu import equeue
    from shadow_tpu.engine import EngineConfig, ShardedRunner, init_state
    from shadow_tpu.engine.round import (
        _flush_outbox_traffic,
        _peek_capacity,
        bootstrap,
        handle_one_iteration,
    )
    from shadow_tpu.engine.sharded import AXIS, auto_a2a_capacity
    from shadow_tpu.events import KIND_PACKET
    from shadow_tpu.graph import NetworkGraph, compute_routing
    from shadow_tpu.models import PholdModel
    from shadow_tpu.simtime import NS_PER_MS, NS_PER_SEC

    ndev = jax.device_count()
    h = hosts or (10240 if jax.default_backend() == "tpu" else 512)
    h -= h % max(ndev, 1)
    graph = NetworkGraph.from_gml(
        "\n".join(
            [
                "graph [",
                "  directed 0",
                *[f"  node [ id {i} ]" for i in range(4)],
                *[
                    f'  edge [ source {i} target {i} latency "1 ms" ]'
                    for i in range(4)
                ],
                *[
                    f'  edge [ source {i} target {j} latency "3 ms" ]'
                    for i in range(4)
                    for j in range(i + 1, 4)
                ],
                "]",
            ]
        )
    )
    tables = compute_routing(graph).with_hosts([i % 4 for i in range(h)])
    cfg = EngineConfig(
        num_hosts=h, runahead_ns=graph.min_latency_ns(), seed=13, tracker=True
    )
    model = PholdModel(
        num_hosts=h, min_delay_ns=1 * NS_PER_MS, max_delay_ns=8 * NS_PER_MS
    )
    st0 = bootstrap(init_state(cfg, model.init()), model, cfg)
    we = jnp.asarray(10**15, jnp.int64)

    @jax.jit
    def _stage(st):
        def body(s, _):
            return handle_one_iteration(s, we, model, tables, cfg), None

        return jax.lax.scan(body, st, None, length=4)[0]

    busy = _stage(st0)
    jax.block_until_ready(busy.events_handled)
    staged = int(np.asarray(busy.outbox.fill).sum())

    def _timed(f, *args):
        jax.block_until_ready(jax.tree.leaves(f(*args))[0])  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            o = f(*args)
            jax.block_until_ready(jax.tree.leaves(o)[0])
            ts.append(time.perf_counter() - t0)
        return round(min(ts) * 1e3, 3)

    # --- single-device phase stages -----------------------------------
    ob = busy.outbox
    h_local, o_cap = ob.valid.shape
    m = h_local * o_cap

    @jax.jit
    def _pool_sort(ob):
        def flat(x):
            return x.reshape((m,) + x.shape[2:])

        valid, dst = flat(ob.valid), flat(ob.dst)
        t, tie, aux, data = flat(ob.time), flat(ob.tie), flat(ob.aux), flat(ob.data)
        key = jnp.where(valid, dst, jnp.int32(1 << 30))
        return jax.lax.sort(
            (key, t, tie, aux, valid, dst)
            + tuple(data[:, i] for i in range(data.shape[1])),
            num_keys=3,
            is_stable=True,
        )

    pooled = _pool_sort(ob)
    _, time_p, tie_p, aux_p, valid_p, dst_p, *data_cols = pooled
    data_p = jnp.stack(data_cols, axis=-1)

    @jax.jit
    def _land_segment(q, dst, valid, t, tie, data, aux):
        return equeue.push_many_segment(
            q=q, dst=dst, valid=valid, time=t, tie=tie,
            kind=jnp.full(valid.shape, KIND_PACKET, jnp.int32),
            data=data, aux=aux,
        )

    @jax.jit
    def _land_dense(q, ob):
        def flat(x):
            return x.reshape((m,) + x.shape[2:])

        lanes = cfg.deliver_lanes if cfg.deliver_lanes > 0 else q.capacity
        return equeue.push_many_sorted(
            deliver_lanes=lanes, q=q, dst=flat(ob.dst), valid=flat(ob.valid),
            time=flat(ob.time), tie=flat(ob.tie),
            kind=jnp.full((m,), KIND_PACKET, jnp.int32),
            data=flat(ob.data), aux=flat(ob.aux),
        )

    peek = jax.jit(_peek_capacity)

    def _check(st):
        return np.asarray(peek(st))

    phases = {
        "capacity_check_ms": _timed(_check, busy),
        "segment": {
            "sort_ms": _timed(_pool_sort, ob),
            "landing_ms": _timed(
                _land_segment, busy.queue, dst_p, valid_p, time_p, tie_p,
                data_p, aux_p,
            ),
            "full_flush_ms": _timed(
                jax.jit(
                    lambda s: _flush_outbox_traffic(
                        s, None, dataclasses.replace(cfg, exchange="segment")
                    )
                ),
                busy,
            ),
        },
        "dense": {
            # the dense grid's three sorts are inside the landing — the
            # per-phase split the segment layout makes possible is the
            # point of the comparison
            "sort_ms": None,
            "landing_ms": _timed(_land_dense, busy.queue, ob),
            "full_flush_ms": _timed(
                jax.jit(
                    lambda s: _flush_outbox_traffic(
                        s, None, dataclasses.replace(cfg, exchange="dense")
                    )
                ),
                busy,
            ),
        },
    }
    out = {
        "hosts": h,
        "staged_events": staged,
        "slot_bytes": _event_slot_bytes(ob),
        "phases": phases,
    }
    print(json.dumps({"exchange_phases": phases}), flush=True)

    # --- sharded: collective phase by per-round delta vs single -------
    if ndev > 1 and h % ndev == 0:
        from jax.sharding import Mesh

        from shadow_tpu.engine.round import run_until

        end = int(0.05 * NS_PER_SEC)
        slot_bytes = out["slot_bytes"]
        rows = []
        measured_hwm = None
        for mode in ("dense", "segment"):
            row = {"mode": mode, "devices": ndev}
            try:
                mcfg = dataclasses.replace(cfg, exchange=mode)
                single = run_until(
                    st0, end, model, tables, mcfg, rounds_per_chunk=16
                )
                t0 = time.perf_counter()
                single = run_until(
                    st0, end, model, tables, mcfg, rounds_per_chunk=16
                )
                jax.block_until_ready(single.events_handled)
                single_wall = time.perf_counter() - t0
                runner = ShardedRunner(
                    Mesh(np.array(jax.devices()), (AXIS,)), model, tables,
                    mcfg, rounds_per_chunk=16,
                    measured_exchange_hwm=measured_hwm,
                )
                s = runner.run_until(st0, end)
                jax.block_until_ready(s.events_handled)
                t0 = time.perf_counter()
                s = runner.run_until(st0, end)
                jax.block_until_ready(s.events_handled)
                wall = time.perf_counter() - t0
                rl = int(np.asarray(s.tracker.rounds_live).max())
                hwm = int(np.asarray(s.tracker.exch_hwm).max())
                cap = auto_a2a_capacity(mcfg, ndev, measured_hwm=measured_hwm)
                row.update(
                    per_round_ms=round(wall / max(rl, 1) * 1e3, 3),
                    exchange_ms_per_round=round(
                        (wall - single_wall) / max(rl, 1) * 1e3, 3
                    ),
                    exch_hwm=hwm,
                    bucket_capacity=cap,
                    bytes_per_host_per_round=round(
                        (ndev - 1) * cap * slot_bytes / (h // ndev), 1
                    ),
                )
                if mode == "dense":
                    measured_hwm = hwm
            except Exception as e:  # noqa: BLE001 — publish the rows that ran
                row["error"] = str(e)[:300]
            rows.append(row)
            print(json.dumps({"exchange_sharded_row": row}), flush=True)
        out["sharded"] = {"devices": ndev, "rows": rows}
    return out


def profile_memory(sizes=(256, 1024, 4096)):
    """Part 10 (memory observatory round): the three memory layers side
    by side per world size — the STATIC priced state (runtime/memtrack.py,
    exact leaf bytes), the COMPILED peak XLA reports for one chunk
    executable (arguments + outputs + temps − donation aliases), and the
    MEASURED device bytes_in_use where the backend exposes memory_stats
    (TPU/GPU; CPU reports none and says so). Also publishes the
    per-subsystem breakdown and checks the dominant grid is the queue's
    [H, C] event rows — the scaling story docs/observability.md tells."""
    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import _run_chunk
    from shadow_tpu.runtime import memtrack

    rows = []
    for hosts in sizes:
        cfg, model, tables, st0 = _build(hosts)
        report = memtrack.price_state(st0, cfg)
        row = {
            "hosts": hosts,
            "static_bytes": report["total_bytes"],
            "bytes_per_host": report["bytes_per_host"],
            "groups": {
                name: g["bytes"] for name, g in report["groups"].items()
            },
            "dominant": report["dominant"]["name"],
            "dominant_is_queue": report["dominant"]["name"].startswith(
                "queue."
            ),
        }
        try:
            exe = (
                jax.jit(_run_chunk, static_argnums=(2, 3, 5))
                .lower(
                    st0, jnp.asarray(10**15, jnp.int64), 8, model, tables,
                    cfg,
                )
                .compile()
            )
            cm = memtrack.compiled_memory(exe)
            if cm:
                row["compiled"] = cm
        except Exception as e:  # noqa: BLE001 — memory analysis is best-effort
            row["compiled"] = {"error": str(e)[:200]}
        dm = memtrack.device_memory()
        row["device"] = dm if dm else "backend reports no memory_stats"
        rows.append(row)
        print(json.dumps({"memory_row": row}), flush=True)
    return {"rows": rows}


def main():
    import jax

    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    eng_hosts = (
        int(sys.argv[2])
        if len(sys.argv) > 2
        else (10240 if jax.default_backend() == "tpu" else 640)
    )
    out = {"backend": jax.default_backend()}
    out["widths"] = profile_widths(reps)
    out["engines"] = profile_engines(reps, eng_hosts)
    out["dispatch"] = profile_dispatch(eng_hosts)
    out["checkpoint"] = profile_checkpoint(eng_hosts)
    out["ensemble"] = profile_ensemble(min(reps, 3))
    out["sweep"] = profile_sweep()
    out["adaptivity"] = profile_adaptivity()
    out["mesh_collectives"] = profile_mesh_collectives()
    out["exchange"] = profile_exchange()
    out["memory"] = profile_memory()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
