"""Kernel-count vs width, and measured per-iteration engine costs.

Part 1 (width scan): compile the plain iteration body at several host
widths on the live backend, print optimized-HLO fusion/kernel counts and
fresh-input timings. If time is ~flat in width while kernel count is
constant, the body is launch-bound and the lever is fewer kernels.

Part 2 (engine comparison, round-6 verdict Next #3): measure the
per-iteration cost of all three round engines — plain (one-event-per-host
handler), pump (XLA microscan, engine/pump.py) and megakernel (fused
Pallas launch, engine/megakernel.py) — on the bench workload's burst
phase. All three are bit-identical, so the comparison starts every
engine from the same mid-burst state and divides wall time by the
drain-loop iterations actually executed (SimState.iters_done). The
resulting table is the one published in docs/megakernel.md.

  python tools/profile_kernels.py [reps] [engine_hosts]

Env knobs: SHADOW_TPU_PROFILE_WIDTHS (comma list, part 1),
SHADOW_TPU_PROFILE_BURST_MS (start,end sim-ms for part 2, default 20,60).
"""

import json
import os
import re
import sys
import time

sys.path.insert(0, ".")


def _fusion_count(compiled_text: str) -> int:
    return len(re.findall(r"^\s*(fusion|%fusion)", compiled_text, re.M))


def profile_widths(reps: int):
    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import handle_one_iteration

    default_widths = (
        "1280,10240" if jax.default_backend() == "tpu" else "640,1280"
    )
    widths_env = os.environ.get("SHADOW_TPU_PROFILE_WIDTHS", default_widths)
    widths = [int(x) for x in widths_env.split(",") if x.strip()]
    we = jnp.asarray(10**15, jnp.int64)
    out = {}
    for hosts in widths:
        cfg, model, tables, st0 = _build(hosts)
        f = jax.jit(lambda s: handle_one_iteration(s, we, model, tables, cfg))
        compiled = f.lower(st0).compile()
        txt = compiled.as_text()
        # fresh-input timing
        st = f(st0)
        jax.block_until_ready(st.events_handled)
        ts = []
        for r in range(reps):
            s_in = st0.replace(rng_counter=st0.rng_counter + r + 1)
            jax.block_until_ready(s_in.rng_counter)
            t0 = time.perf_counter()
            o = f(s_in)
            jax.block_until_ready(o.events_handled)
            ts.append(time.perf_counter() - t0)
        out[hosts] = {
            "fusions": _fusion_count(txt),
            "hlo_lines": txt.count("\n"),
            "best_ms": round(min(ts) * 1e3, 2),
        }
        print(hosts, out[hosts], flush=True)
    return out


def profile_engines(reps: int, hosts: int):
    """Per-iteration cost of plain vs pump vs megakernel on the burst
    phase: identical start state (the engines are bit-identical, so any
    engine may produce it), wall divided by drain-loop iterations."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import run_round, run_until

    burst_env = os.environ.get("SHADOW_TPU_PROFILE_BURST_MS", "20,60")
    b0_ms, b1_ms = [int(x) for x in burst_env.split(",")]
    b0, b1 = b0_ms * 1_000_000, b1_ms * 1_000_000

    cfg0, model, tables, st0 = _build(hosts)
    st_burst = run_until(st0, b0, model, tables, cfg0, rounds_per_chunk=32)
    jax.block_until_ready(st_burst.events_handled)
    iters0 = int(np.asarray(st_burst.iters_done).sum())
    ev0 = int(np.asarray(st_burst.events_handled).sum())

    variants = {
        "plain": dataclasses.replace(cfg0, engine="plain", pump_k=0),
        "pump": dataclasses.replace(cfg0, engine="pump", pump_k=8),
        "megakernel": dataclasses.replace(
            cfg0, engine="megakernel", pump_k=8
        ),
    }
    out = {}
    for name, cfg in variants.items():
        row = {}
        try:
            we = jnp.asarray(b0 + cfg.runahead_ns, jnp.int64)
            body = jax.jit(
                lambda s, c=cfg: run_round(s, we, model, tables, c)
            )
            row["fusions"] = _fusion_count(
                body.lower(st_burst).compile().as_text()
            )
            # warm the chunked executable, then time the burst window
            s = run_until(
                st_burst, b1, model, tables, cfg, rounds_per_chunk=32
            )
            jax.block_until_ready(s.events_handled)
            walls = []
            for _ in range(reps):
                t0 = time.perf_counter()
                s = run_until(
                    st_burst, b1, model, tables, cfg, rounds_per_chunk=32
                )
                jax.block_until_ready(s.events_handled)
                walls.append(time.perf_counter() - t0)
            wall = min(walls)
            iters = int(np.asarray(s.iters_done).sum()) - iters0
            events = int(np.asarray(s.events_handled).sum()) - ev0
            row.update(
                wall_s=round(wall, 3),
                iters=iters,
                events=events,
                us_per_iter=round(wall / max(iters, 1) * 1e6, 1),
                ns_per_event=round(wall / max(events, 1) * 1e9, 1),
            )
        except Exception as e:  # noqa: BLE001 — a backend that cannot
            # lower one engine must not kill the comparison of the others
            row["error"] = str(e)[:300]
        out[name] = row
        print(json.dumps({"engine": name, **row}), flush=True)
    if "us_per_iter" in out.get("plain", {}):
        for name in ("pump", "megakernel"):
            if "us_per_iter" in out.get(name, {}):
                out[name]["iter_cost_vs_plain"] = round(
                    out[name]["us_per_iter"] / out["plain"]["us_per_iter"], 3
                )
    return out


def main():
    import jax

    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    eng_hosts = (
        int(sys.argv[2])
        if len(sys.argv) > 2
        else (10240 if jax.default_backend() == "tpu" else 640)
    )
    out = {"backend": jax.default_backend()}
    out["widths"] = profile_widths(reps)
    out["engines"] = profile_engines(reps, eng_hosts)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
