"""Kernel-count vs width: compile the iteration body at several host
widths on the live backend, print optimized-HLO fusion/kernel counts and
fresh-input timings. If time is ~flat in width while kernel count is
constant, the body is launch-bound and the lever is fewer kernels.

  python tools/profile_kernels.py [reps]
"""

import re
import sys
import time

sys.path.insert(0, ".")


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 4

    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import handle_one_iteration

    we = jnp.asarray(10**15, jnp.int64)
    out = {}
    for hosts in (1280, 10240):
        cfg, model, tables, st0 = _build(hosts)
        f = jax.jit(lambda s: handle_one_iteration(s, we, model, tables, cfg))
        lowered = f.lower(st0)
        compiled = lowered.compile()
        txt = compiled.as_text()
        kernels = len(re.findall(r"^\s*(fusion|%fusion)", txt, re.M))
        ops = txt.count("\n")
        # fresh-input timing
        st = f(st0)
        jax.block_until_ready(st.events_handled)
        ts = []
        for r in range(reps):
            s_in = st0.replace(rng_counter=st0.rng_counter + r + 1)
            jax.block_until_ready(s_in.rng_counter)
            t0 = time.perf_counter()
            o = f(s_in)
            jax.block_until_ready(o.events_handled)
            ts.append(time.perf_counter() - t0)
        out[hosts] = {
            "fusions": kernels,
            "hlo_lines": ops,
            "best_ms": round(min(ts) * 1e3, 2),
        }
        print(hosts, out[hosts], flush=True)
    print(out, flush=True)


if __name__ == "__main__":
    main()
