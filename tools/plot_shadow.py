#!/usr/bin/env python3
"""Plot per-host throughput over simulated time from parse_shadow.py
output (the analogue of the reference's src/tools/plot-shadow.py).
Writes an SVG without needing matplotlib.

Usage: plot_shadow.py parsed.json -o plot.svg
"""

from __future__ import annotations

import argparse
import json
import sys


def _sim_seconds(ts: str) -> float:
    """Seconds since the 2000-01-01 sim epoch (date included so multi-day
    simulations stay monotonic)."""
    import datetime

    parts = ts.split()
    clock = parts[-1]
    h, m, s = clock.split(":")
    secs = int(h) * 3600 + int(m) * 60 + float(s)
    if len(parts) == 2:
        d = datetime.date.fromisoformat(parts[0])
        secs += (d - datetime.date(2000, 1, 1)).days * 86400.0
    return secs


def render_svg(parsed: dict, width=800, height=400) -> str:
    hosts = parsed.get("hosts", {})
    series = []
    for host, samples in sorted(hosts.items()):
        pts = [(_sim_seconds(s["sim_time"]), s["bytes_recv"]) for s in samples]
        if pts:
            series.append((host, pts))
    if not series:
        return "<svg xmlns='http://www.w3.org/2000/svg'/>"
    tmax = max(t for _, pts in series for t, _ in pts) or 1.0
    vmax = max(v for _, pts in series for _, v in pts) or 1
    pad = 40
    out = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' height='{height}'>",
        f"<text x='{pad}' y='16' font-size='12'>bytes received vs simulated seconds</text>",
    ]
    colors = ["#4363d8", "#e6194b", "#3cb44b", "#f58231", "#911eb4", "#46f0f0"]
    for i, (host, pts) in enumerate(series):
        path = " ".join(
            f"{'M' if j == 0 else 'L'}"
            f"{pad + t / tmax * (width - 2 * pad):.1f},"
            f"{height - pad - v / vmax * (height - 2 * pad):.1f}"
            for j, (t, v) in enumerate(pts)
        )
        c = colors[i % len(colors)]
        out.append(f"<path d='{path}' fill='none' stroke='{c}' stroke-width='1.5'/>")
        out.append(
            f"<text x='{width - pad + 2}' y='{20 + 14 * i}' font-size='10' fill='{c}'>{host}</text>"
        )
    out.append(
        f"<line x1='{pad}' y1='{height - pad}' x2='{width - pad}' y2='{height - pad}' stroke='#333'/>"
    )
    out.append(f"<line x1='{pad}' y1='{pad}' x2='{pad}' y2='{height - pad}' stroke='#333'/>")
    out.append("</svg>")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("parsed_json")
    ap.add_argument("-o", "--output", default="shadow-plot.svg")
    args = ap.parse_args(argv)
    with open(args.parsed_json) as f:
        parsed = json.load(f)
    svg = render_svg(parsed)
    with open(args.output, "w") as f:
        f.write(svg)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
