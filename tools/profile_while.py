"""Isolate the lax.while_loop penalty in run_round (the 59us-scan vs
34ms-while discrepancy): time 8 real rounds of the bench world three
ways on an ACTIVE state —

  while:   the current run_round (while_loop until drained)
  block:   while(any eligible) over a scan of K iterations (amortizes
           whatever per-while-iteration cost exists K-fold)
  scan:    fixed scan of T iterations per round, no while at all
           (extra iterations are masked no-ops; correctness-neutral)

  python tools/profile_while.py [hosts] [rounds] [K] [T]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    nrounds = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    t_fixed = int(sys.argv[4]) if len(sys.argv) > 4 else 48

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu import equeue
    from shadow_tpu.engine.round import (
        _next_window_end,
        flush_outbox,
        handle_one_iteration,
        run_round,
    )

    cfg, model, tables, st0 = _build(hosts)

    def rounds_while(s):
        def one(s, _):
            we = _next_window_end(s, jnp.asarray(10**18, jnp.int64), cfg, None)
            return run_round(s, we, model, tables, cfg), None
        s, _ = jax.lax.scan(one, s, None, length=nrounds)
        return s

    def round_block(s, we):
        def cond(c):
            s, it = c
            return jnp.any(equeue.next_time(s.queue) < we) & (
                it < 100_000
            )

        def body(c):
            s, it = c
            def inner(s, _):
                return handle_one_iteration(s, we, model, tables, cfg), None
            s, _ = jax.lax.scan(inner, s, None, length=k)
            return s, it + k

        (s, it), = (jax.lax.while_loop(cond, body, (s, jnp.int32(0))),)
        s = flush_outbox(s, None, cfg)
        return s.replace(
            now=jnp.maximum(s.now, we), iters_done=s.iters_done.at[0].add(it)
        )

    def rounds_block(s):
        def one(s, _):
            we = _next_window_end(s, jnp.asarray(10**18, jnp.int64), cfg, None)
            return round_block(s, we), None
        s, _ = jax.lax.scan(one, s, None, length=nrounds)
        return s

    def rounds_scan(s):
        def one(s, _):
            we = _next_window_end(s, jnp.asarray(10**18, jnp.int64), cfg, None)
            def inner(s, _):
                return handle_one_iteration(s, we, model, tables, cfg), None
            s, _ = jax.lax.scan(inner, s, None, length=t_fixed)
            s = flush_outbox(s, None, cfg)
            return s.replace(now=jnp.maximum(s.now, we)), None
        s, _ = jax.lax.scan(one, s, None, length=nrounds)
        return s

    results = {"backend": jax.default_backend(), "hosts": hosts,
               "rounds": nrounds, "k": k, "t_fixed": t_fixed}
    for name, fn in (("while", rounds_while), ("block", rounds_block),
                     ("scan", rounds_scan)):
        print(f"compiling {name}...", flush=True)
        f = jax.jit(fn)
        out = f(st0)
        jax.block_until_ready(out.events_handled)
        t0 = time.perf_counter()
        out = f(st0)
        jax.block_until_ready(out.events_handled)
        dt = time.perf_counter() - t0
        ev = int(np.asarray(out.events_handled).sum())
        it = int(np.asarray(out.iters_done).sum())
        results[name] = {"s": round(dt, 4), "events": ev, "iters": it}
        print(name, results[name], flush=True)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
