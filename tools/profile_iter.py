"""Per-iteration cost breakdown of the round engine on the live backend.

Times one jitted pop-iteration (full-width and compacted), the round
boundary flush, and isolated stages, at bench shapes. Drives the
throughput work: if T(compact-128) ~= T(full-8192), the iteration is
op-dispatch-bound, not memory-bound, and the lever is fewer iterations /
fewer fused kernels, not smaller tensors.

  python tools/profile_iter.py [hosts] [reps]
"""

import sys
import time

sys.path.insert(0, ".")


def bench_fn(fn, *args, reps=50):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 30

    import dataclasses

    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import (
        flush_outbox,
        handle_one_iteration,
        handle_one_iteration_compact,
        run_round,
    )

    cfg, model, tables, st0 = _build(hosts)
    we = jnp.asarray(40_000_000, jnp.int64)

    # run a few real rounds first so queues hold a realistic backlog
    print("compiling warm round...", flush=True)
    warm = jax.jit(lambda s: run_round(s, we, model, tables, cfg))
    st = warm(st0)
    jax.block_until_ready(st.events_handled)

    results = {"backend": jax.default_backend(), "hosts": hosts}

    it_full = jax.jit(lambda s: handle_one_iteration(s, we, model, tables, cfg))
    results["iter_full_ms"] = round(bench_fn(it_full, st, reps=reps) * 1e3, 3)
    print("iter_full_ms", results["iter_full_ms"], flush=True)

    for lanes in (1024, 128):
        itc = jax.jit(
            lambda s, L=lanes: handle_one_iteration_compact(s, we, model, tables, cfg, L)
        )
        results[f"iter_compact{lanes}_ms"] = round(bench_fn(itc, st, reps=reps) * 1e3, 3)
        print(f"iter_compact{lanes}_ms", results[f"iter_compact{lanes}_ms"], flush=True)

    fl = jax.jit(lambda s: flush_outbox(s, None, cfg))
    results["flush_ms"] = round(bench_fn(fl, st, reps=reps) * 1e3, 3)
    print("flush_ms", results["flush_ms"], flush=True)

    # isolated: queue pop only
    from shadow_tpu import equeue

    pop = jax.jit(lambda s: equeue.pop_min(s.queue, equeue.next_time(s.queue) < we)[1].count)
    results["pop_only_ms"] = round(bench_fn(pop, st, reps=reps) * 1e3, 3)
    print("pop_only_ms", results["pop_only_ms"], flush=True)

    # model handler only (with a fake popped event)
    def handler_only(s):
        ev, q = equeue.pop_min(s.queue, equeue.next_time(s.queue) < we)
        from shadow_tpu.engine.round import Draw

        d = Draw(s.rng_key, s.rng_counter)
        mstate, lemits, pemits = model.handle(s.model, ev, d, cfg, s.host_id)
        return jax.tree.map(lambda a: a.sum() if hasattr(a, "sum") else a, (lemits.valid, pemits.valid, mstate.streams_done))

    h = jax.jit(handler_only)
    results["pop_plus_handler_ms"] = round(bench_fn(h, st, reps=reps) * 1e3, 3)
    print("pop_plus_handler_ms", results["pop_plus_handler_ms"], flush=True)

    # one full round (many iterations) for iteration-count estimation
    t0 = time.perf_counter()
    st2 = warm(st)
    jax.block_until_ready(st2.events_handled)
    results["one_round_s"] = round(time.perf_counter() - t0, 3)
    results["events_round2"] = int(
        jax.device_get(st2.events_handled.sum() - st.events_handled.sum())
    )

    import json

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
