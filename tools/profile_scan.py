"""On-device per-phase cost: time big compiled scans (one dispatch each)
so the axon tunnel's ~13 ms per-call overhead cannot contaminate the
numbers (tools/profile_iter.py's standalone timings all sit on that
floor). Phases: N pop-iterations (no flush), N outbox flushes, N full
rounds, and N iterations with the model handler replaced by an identity
(isolates the 15k-op tgen/TCP handler from queue mechanics).

  python tools/profile_scan.py [hosts] [N]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def timed(fn, *args):
    import jax

    out = fn(*args)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 64

    import jax
    import jax.numpy as jnp

    from bench import _build
    from shadow_tpu.engine.round import (
        flush_outbox,
        handle_one_iteration,
        run_round,
    )

    cfg, model, tables, st0 = _build(hosts)
    we = jnp.asarray(40_000_000, jnp.int64)

    print("warming one round...", flush=True)
    warm = jax.jit(lambda s: run_round(s, we, model, tables, cfg))
    st = warm(st0)
    jax.block_until_ready(st.events_handled)

    results = {"backend": jax.default_backend(), "hosts": hosts, "n": n}

    def scan_iters(s):
        def body(s, _):
            return handle_one_iteration(s, we, model, tables, cfg), None
        s, _ = jax.lax.scan(body, s, None, length=n)
        return s

    def scan_flush(s):
        def body(s, _):
            return flush_outbox(s, None, cfg), None
        s, _ = jax.lax.scan(body, s, None, length=n)
        return s

    def scan_rounds(s):
        def body(s, _):
            return run_round(s, we, model, tables, cfg), None
        s, _ = jax.lax.scan(body, s, None, length=n)
        return s

    class _IdModel:
        """Identity handler with tgen's emit shapes: isolates queue
        mechanics + netstack from the TCP handler's op count."""
        LOCAL_EMITS = model.LOCAL_EMITS
        PACKET_EMITS = model.PACKET_EMITS
        DRAWS_PER_EVENT = 0
        BOOTSTRAP_DRAWS = 0
        LOSS_COUNTER_LANE = None

        def __hash__(self):
            return 1

        def __eq__(self, other):
            return isinstance(other, _IdModel)

        def handle(self, mstate, ev, draw, cfg_, host_id):
            from shadow_tpu.engine.state import (
                empty_local_emits,
                empty_packet_emits,
            )
            h = host_id.shape[0]
            return mstate, empty_local_emits(h, self.LOCAL_EMITS), \
                empty_packet_emits(h, self.PACKET_EMITS)

    idm = _IdModel()

    def scan_iters_noop(s):
        def body(s, _):
            return handle_one_iteration(s, we, idm, tables, cfg), None
        s, _ = jax.lax.scan(body, s, None, length=n)
        return s

    for name, fn in (
        ("iters", scan_iters),
        ("iters_noop_handler", scan_iters_noop),
        ("flush", scan_flush),
        ("rounds", scan_rounds),
    ):
        print(f"compiling {name}...", flush=True)
        f = jax.jit(fn)
        t = timed(f, st)
        results[f"{name}_ms_per"] = round(t / n * 1e3, 3)
        print(name, results[f"{name}_ms_per"], flush=True)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
