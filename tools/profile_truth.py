"""Ground-truth timings on the live backend with FRESH inputs per call.

profile_while/profile_scan re-invoke the same jitted fn with the SAME
input buffers; if any layer (axon relay or client) dedupes identical
executions, their numbers collapse to the tunnel floor and lie (round-4's
59us-scan reading). Every timed call here perturbs the input state (a
different rng_counter bump), so no layer can serve a cached result.

Measures, at bench shapes:
  call_floor        jit identity on the state (tunnel + dispatch floor)
  while_trivial     while_loop of N counter bumps (no body work)
  scan_body[N]      scan of N handle_one_iteration bodies, fresh input
  while_body[N]     while-loop-driven N bodies (cond: iters < N), fresh
  round_while       the real run_round (8 real rounds, fresh input)
  flush             one flush_outbox per call, fresh input

  python tools/profile_truth.py [hosts] [reps]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    hosts = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _build
    from shadow_tpu.engine.round import (
        _next_window_end,
        flush_outbox,
        handle_one_iteration,
        run_round,
    )

    cfg, model, tables, st0 = _build(hosts)
    we_far = jnp.asarray(10**18, jnp.int64)

    # a realistic mid-sim state: run a few rounds first
    warm = jax.jit(
        lambda s: run_round(
            s, _next_window_end(s, we_far, cfg, None), model, tables, cfg
        )
    )
    st = st0
    for _ in range(3):
        st = warm(st)
    jax.block_until_ready(st.events_handled)

    results = {"backend": jax.default_backend(), "hosts": hosts}

    def timed(name, fn, n_inner=1):
        f = jax.jit(fn)
        out = f(st, jnp.uint32(999))  # compile
        jax.block_until_ready(out)
        ts = []
        for r in range(reps):
            s_in = st
            t0 = time.perf_counter()
            out = f(s_in, jnp.uint32(r))  # fresh scalar => fresh execution
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        best = min(ts)
        results[name] = {
            "ms": round(best * 1e3, 3),
            "ms_per_inner": round(best * 1e3 / n_inner, 4),
        }
        print(name, results[name], flush=True)

    # tunnel + dispatch floor: return a scalar derived from the state
    timed("call_floor", lambda s, r: s.events_handled.sum() + r)

    # while_loop overhead with a trivial body (r keeps inputs fresh
    # without changing the 64-iteration trip count)
    def while_trivial(s, r):
        def cond(c):
            return c[0] < 64
        def body(c):
            return (c[0] + 1, c[1] + c[0])
        i, acc = jax.lax.while_loop(cond, body, (r * 0, jnp.uint32(0)))
        return acc + s.events_handled[0] + r
    timed("while_trivial_64", while_trivial, n_inner=64)

    we = jnp.asarray(int(np.asarray(st.now)) + 10**15, jnp.int64)

    def mk_scan(n):
        def f(s, r):
            s = s.replace(rng_counter=s.rng_counter + r * 0)
            s = s.replace(seq=s.seq + r * 0)

            def inner(s, _):
                return handle_one_iteration(s, we, model, tables, cfg), None

            s, _ = jax.lax.scan(inner, s, None, length=n)
            return s.events_handled.sum() + r
        return f

    def mk_while(n):
        def f(s, r):
            def cond(c):
                return c[1] < n

            def body(c):
                s, i = c
                return handle_one_iteration(s, we, model, tables, cfg), i + 1

            s, _ = jax.lax.while_loop(cond, body, (s, r * 0))
            return s.events_handled.sum() + r
        return f

    timed("scan_body_16", mk_scan(16), n_inner=16)
    timed("while_body_16", mk_while(16), n_inner=16)
    timed("scan_body_64", mk_scan(64), n_inner=64)

    def one_flush(s, r):
        s = s.replace(rng_counter=s.rng_counter + r * 0)
        s = flush_outbox(s, None, cfg)
        return s.queue.count.sum() + r
    timed("flush", one_flush)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
