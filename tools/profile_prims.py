"""Primitive-cost table for the axon TPU: element gather vs scatter vs
multi-operand sort vs gather-of-slices vs searchsorted at exchange-relevant
sizes. Each measured inside a length-N scan (one dispatch), with the
result folded into the carry so nothing is dead-code-eliminated.

  python tools/profile_prims.py [N]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32

    import jax
    import jax.numpy as jnp
    import numpy as np

    import shadow_tpu  # noqa: F401  (x64)

    key = jax.random.key(0)
    results = {"backend": jax.default_backend(), "n": n}

    def timed(name, body, *arrs):
        def f(c):
            def step(c, _):
                out = body(*arrs, c)
                return out, None
            c, _ = jax.lax.scan(step, c, None, length=n)
            return c
        g = jax.jit(f)
        c0 = jnp.zeros((), jnp.int64)
        out = g(c0)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = g(c0)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n * 1e3
        results[name] = round(dt, 3)
        print(name, round(dt, 3), "ms", flush=True)

    H, Q, O = 10240, 384, 32
    m = H * O

    src64 = jax.random.randint(key, (m,), 0, 1 << 40, dtype=jnp.int64)
    idx_m = jax.random.randint(key, (m,), 0, m, dtype=jnp.int32)
    idx_hk16 = jax.random.randint(key, (H, 16), 0, m, dtype=jnp.int32)
    big2d = jax.random.randint(key, (H, Q), 0, 1 << 40, dtype=jnp.int64)
    sdst = jax.random.randint(key, (m,), 0, H, dtype=jnp.int32)
    sslot = jax.random.randint(key, (m,), 0, Q, dtype=jnp.int32)
    starts = jax.random.randint(key, (H,), 0, m - Q, dtype=jnp.int32)
    keys_m = jax.random.randint(key, (m,), 0, H + 1, dtype=jnp.int32)
    p_ops = [jax.random.randint(jax.random.fold_in(key, i), (m,), 0, 1 << 30,
                                dtype=jnp.int32) for i in range(10)]

    # element gather m from m (i64)
    timed("gather_elem_327k_i64",
          lambda s, i, c: s[(i + c.astype(jnp.int32)) % m].sum() + c, src64, idx_m)
    # element gather [H,16] from m
    timed("gather_elem_164k_i64",
          lambda s, i, c: s[(i + c.astype(jnp.int32)) % m].sum() + c, src64, idx_hk16)
    # scatter m into [H,Q]
    timed("scatter_327k_i64",
          lambda b, d, sl, c: b.at[d, (sl + c.astype(jnp.int32)) % Q]
          .set(jnp.int64(1), mode="drop").sum() + c, big2d, sdst, sslot)
    # gather-of-slices: H slices of length 48 from m
    def gos(s, st, c):
        st = (st + c.astype(jnp.int32)) % (m - 48)
        out = jax.vmap(lambda o: jax.lax.dynamic_slice(s, (o,), (48,)))(st)
        return out.sum() + c
    timed("gather_slices_Hx48_i64", gos, src64, starts)
    # 2-operand sort (key + index)
    timed("sort_2op_327k",
          lambda k2, c: jax.lax.sort((k2 + c.astype(jnp.int32),
                                      jnp.arange(m, dtype=jnp.int32)),
                                     num_keys=1)[1].sum().astype(jnp.int64) + c,
          keys_m)
    # 12-operand sort (key + 64-bit payload split + 8 lanes + aux)
    def sort12(k2, c):
        ops = (k2 + c.astype(jnp.int32),) + tuple(p_ops)
        out = jax.lax.sort(ops, num_keys=1)
        return out[1].sum().astype(jnp.int64) + c
    timed("sort_11op_327k", sort12, keys_m)
    # searchsorted both methods
    hosts = jnp.arange(H, dtype=jnp.int32)
    ks = jnp.sort(keys_m)
    timed("searchsorted_scan",
          lambda s, c: jnp.searchsorted(s, hosts, method="scan").sum()
          .astype(jnp.int64) + c, ks)
    timed("searchsorted_sort",
          lambda s, c: jnp.searchsorted(s, hosts, method="sort").sum()
          .astype(jnp.int64) + c, ks)
    # dense one-hot 16-lane merge into [H,Q] (the delivery-merge pattern)
    lanes = jax.random.randint(key, (H, 16), 0, 1 << 40, dtype=jnp.int64)
    cnt = jax.random.randint(key, (H,), 0, Q - 16, dtype=jnp.int32)
    def dense_merge(b, ln, c):
        qi = jnp.arange(Q, dtype=jnp.int32)[None, :]
        k = qi - cnt[:, None] + (c % 2).astype(jnp.int32)
        take = (k >= 0) & (k < 16)
        picked = jnp.take_along_axis(ln, jnp.clip(k, 0, 15), axis=1)
        return jnp.where(take, picked, b).sum() + c
    timed("dense_merge_16lane_HxQ", dense_merge, big2d, lanes)

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
